"""R1v2 jit-host-sync-xmod: cross-module host-sync reachability.

R1 proper is module-local — its closure stops at the file boundary, so a
`.item()` in telemetry.py or health.py that is reachable from the jitted
growth carry (or sits on the per-iteration dispatch path the engine loop
drives) is invisible to it. This pass walks the package call graph
instead:

* **trace surface** — the forward closure of every jit boundary
  (decorator-jitted defs, `jax.jit(fn)` aliases, factory products) over
  resolved call / callback-ref / shard_map-wrap edges. Any function in
  that closure runs under trace; the full R1 sink catalogue applies.
  Functions already covered by the module-local R1 closure are skipped —
  one finding per defect, owned by the more precise rule.
* **hot dispatch surface** — functions transitively called from loop
  bodies inside dispatch-capable functions (functions that themselves
  reach the trace surface). These run per-iteration on the host side of
  the boundary: a blocking pull here serializes the dispatch pipeline
  even though it never traces. To keep this surface from flooding
  (checkpoint-style cold paths are reachable too), only the
  unambiguously-blocking sinks are flagged: `.item()` / `.tolist()` /
  `.block_until_ready()` (including the `getattr(obj, attr)` form looped
  over a literal method tuple), `bool()` of a non-static value,
  `np.asarray`/`np.array`, and `jax.device_get`. `int()`/`float()` of
  scalars stay out — they dominate cold config/checkpoint code and carry
  no pipeline cost there. Two module groups are excluded: the ones R1
  already polices (ops/, treelearner/, models/gbdt.py — their loops are
  checked by R1's own driver-side pass), and the host-API compat layer
  (basic/engine/sklearn/config/io/models shims), whose contract IS host
  numpy — per-iteration pulls there are the price of the LightGBM-
  compatible interface, not a defect this rule can see past. What
  remains is the hot-loop HOOK surface: telemetry.py, health.py,
  checkpoint.py, utils/ and parallel/ — instrumentation invoked from
  inside the dispatch loop, where a hidden sync stalls the pipeline
  every iteration.

Findings anchor at the SINK, so the fix or the reasoned suppression lives
next to the offending line in the hook module; the message names the
cross-module entry that makes the line hot.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..callgraph import (CallGraph, Node, _own_calls, _own_statements,
                         get_callgraph)
from ..core import Package, Violation, dotted_name, in_scope
from .base import Rule, module_functions
from .jit_boundary import (JitBoundaryRule, _HOST_METHODS, _JAX_HOST,
                           _is_jitted, _static_under_jit)

_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_NP_PULLS = {"asarray", "array"}


def _top_qual(node: Node) -> str:
    """Map a graph node to the module_functions() qual that v1 checks:
    nested defs collapse onto their top-level ancestor."""
    qual = node.qual.split(":", 1)[1]
    parts = qual.split(".")
    if node.cls is not None:
        return ".".join(parts[:2])
    return parts[0]


def _local_v1_closure(ctx) -> Set[str]:
    """Replicate R1's module-local jit closure (same short-name edges) so
    this pass never double-reports a sink R1 already owns."""
    funcs = dict(module_functions(ctx.tree))
    short: Dict[str, List[str]] = {}
    for qual in funcs:
        short.setdefault(qual.rsplit(".", 1)[-1], []).append(qual)

    def callees(fn: ast.AST) -> Set[str]:
        found: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in short:
                found.update(short[f.id])
            elif isinstance(f, ast.Attribute) and f.attr in short:
                found.update(short[f.attr])
        return found

    reachable: Set[str] = {q for q, fn in funcs.items() if _is_jitted(fn)}
    frontier = set(reachable)
    while frontier:
        nxt: Set[str] = set()
        for qual in frontier:
            nxt |= callees(funcs[qual]) - reachable
        reachable |= nxt
        frontier = nxt
    return reachable


def _getattr_sync_names(node: Node) -> Set[str]:
    """Names bound via `name = getattr(obj, var, ...)` where `var` loops
    over a literal tuple containing a host-sync method name — telemetry's
    `for attr in ("item", "tolist"): fn = getattr(v, attr); ... fn()`."""
    body = node.node if node.node is not None else node.ctx.tree
    loop_vars: Set[str] = set()
    for sub in _own_statements(body):
        if not isinstance(sub, (ast.For, ast.AsyncFor)):
            continue
        if not isinstance(sub.target, ast.Name):
            continue
        it = sub.iter
        if isinstance(it, (ast.Tuple, ast.List)) and any(
                isinstance(e, ast.Constant) and e.value in _HOST_METHODS
                for e in it.elts):
            loop_vars.add(sub.target.id)
    if not loop_vars:
        return set()
    names: Set[str] = set()
    for sub in _own_statements(body):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call) \
                and dotted_name(sub.value.func) == "getattr" \
                and len(sub.value.args) >= 2 \
                and isinstance(sub.value.args[1], ast.Name) \
                and sub.value.args[1].id in loop_vars:
            for tgt in sub.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


class JitBoundaryXModRule(Rule):
    name = "jit-host-sync-xmod"
    code = "R1"  # same family as jit-host-sync: disable=R1 covers both
    description = ("host sync reachable from a jit boundary or the hot "
                   "dispatch loop through a cross-module call chain")
    # whole-program: the call graph decides what is hot, not the path
    scope_prefixes = ()
    scope_exact = ()
    whole_program = True
    # pass B only fires inside the hook surface (see module docstring)
    hook_prefixes = ("parallel/", "utils/")
    hook_exact = ("telemetry.py", "health.py", "checkpoint.py")

    def check(self, pkg: Package) -> Iterable[Violation]:
        graph = get_callgraph(pkg)
        v1 = JitBoundaryRule()
        locally_covered: Dict[str, Set[str]] = {}
        for ctx in pkg.files:
            if ctx.tree is None:
                continue
            if in_scope(ctx, v1.scope_prefixes, v1.scope_exact):
                locally_covered[ctx.relpath] = _local_v1_closure(ctx)

        def covered_by_v1(node: Node) -> bool:
            cov = locally_covered.get(node.ctx.relpath)
            return cov is not None and _top_qual(node) in cov

        out: List[Violation] = []
        seen: Set[Tuple[str, int, int]] = set()

        # ---- pass A: the global trace surface --------------------------
        parents: Dict[str, Optional[Tuple[str, int]]] = {}
        frontier: List[str] = []
        for q in sorted(graph.jit_seeds()):
            parents[q] = None
            frontier.append(q)
        closure: Set[str] = set()
        while frontier:
            q = frontier.pop(0)
            if q in closure or q not in graph.nodes:
                continue
            closure.add(q)
            for e in graph.nodes[q].edges:
                if e.target is None or e.target in parents:
                    continue
                site = (graph.nodes[q].ctx.relpath,
                        e.call.lineno if e.call is not None else 1)
                parents[e.target] = site
                frontier.append(e.target)

        for q in sorted(closure):
            node = graph.nodes[q]
            if node.node is None or covered_by_v1(node):
                continue
            entry = parents.get(q)
            via = (" (jit-reachable via %s:%d)" % entry) if entry \
                else " (jit boundary)"
            out.extend(self._trace_sinks(node, via, seen))

        # ---- pass B: the hot dispatch surface --------------------------
        dispatch: Set[str] = set(closure)
        callers = graph.callers()
        grew = True
        while grew:
            grew = False
            for q in list(dispatch):
                for e in callers.get(q, ()):  # who calls into the surface
                    if e.kind == "call" and e.src not in dispatch:
                        dispatch.add(e.src)
                        grew = True

        hot_parents: Dict[str, Tuple[str, int]] = {}
        hot_frontier: List[str] = []
        for q in sorted(dispatch):
            node = graph.nodes[q]
            body = node.node if node.node is not None else node.ctx.tree
            if body is None:
                continue
            for stmt in _own_statements(body):
                if not isinstance(stmt, _LOOPS):
                    continue
                for call in _own_calls_within(body, stmt):
                    for ref in graph.resolve_call(node, call):
                        if ref.target is None:
                            continue
                        for tq in ref.target.split("|"):
                            if tq not in hot_parents:
                                hot_parents[tq] = (node.ctx.relpath,
                                                   stmt.lineno)
                                hot_frontier.append(tq)
        hot: Set[str] = set()
        while hot_frontier:
            q = hot_frontier.pop(0)
            if q in hot or q not in graph.nodes:
                continue
            hot.add(q)
            for e in graph.nodes[q].edges:
                if e.target is not None and e.target not in hot_parents:
                    hot_parents[e.target] = hot_parents[q]
                    hot_frontier.append(e.target)

        for q in sorted(hot):
            node = graph.nodes[q]
            if node.node is None or q in closure:
                continue
            if in_scope(node.ctx, v1.scope_prefixes, v1.scope_exact):
                continue  # R1's own driver-side loop pass owns these
            if not in_scope(node.ctx, self.hook_prefixes, self.hook_exact):
                continue  # host-API compat layer: host numpy by contract
            loop_site = hot_parents[q]
            out.extend(self._hot_sinks(node, loop_site, seen))
        return out

    # ------------------------------------------------------------ sinks

    def _trace_sinks(self, node: Node, via: str,
                     seen: Set[Tuple[str, int, int]]) -> List[Violation]:
        """Full R1 sink catalogue over the node's own calls (nested defs
        are their own graph nodes)."""
        from .jit_boundary import _HOST_BUILTINS, _NP_CALLS
        out: List[Violation] = []
        body = node.node
        qual = node.qual
        for call in _own_calls(body):
            f = call.func
            fname = dotted_name(f)
            msg = None
            if isinstance(f, ast.Name) and f.id in _HOST_BUILTINS:
                if call.args and not all(_static_under_jit(a)
                                         for a in call.args):
                    msg = ("%s() concretizes a traced value inside %r%s"
                           % (f.id, qual, via))
            elif isinstance(f, ast.Attribute) and f.attr in _HOST_METHODS:
                msg = (".%s() is a device->host sync inside %r%s"
                       % (f.attr, qual, via))
            elif fname.startswith("np.") and fname[3:] in _NP_CALLS:
                msg = ("%s() pulls traced data to host inside %r%s"
                       % (fname, qual, via))
            elif fname in _JAX_HOST:
                msg = "%s() inside %r%s" % (fname, qual, via)
            if msg is None:
                continue
            key = (node.ctx.relpath, call.lineno, call.col_offset)
            if key in seen:
                continue
            seen.add(key)
            out.append(self.violation(node.ctx, call, msg))
        return out

    def _hot_sinks(self, node: Node, loop_site: Tuple[str, int],
                   seen: Set[Tuple[str, int, int]]) -> List[Violation]:
        out: List[Violation] = []
        body = node.node
        qual = node.qual
        getattr_syncs = _getattr_sync_names(node)
        where = ("on the hot dispatch path (reached from the loop at "
                 "%s:%d)" % loop_site)
        for call in _own_calls(body):
            f = call.func
            fname = dotted_name(f)
            msg = None
            if isinstance(f, ast.Attribute) and f.attr in _HOST_METHODS:
                msg = (".%s() blocks per iteration inside %r %s"
                       % (f.attr, qual, where))
            elif isinstance(f, ast.Name) and f.id == "bool":
                if call.args and not all(_static_under_jit(a)
                                         for a in call.args):
                    msg = ("bool() forces a device sync inside %r %s"
                           % (qual, where))
            elif isinstance(f, ast.Name) and f.id in getattr_syncs:
                msg = ("call of %r resolved from a host-sync method tuple "
                       "via getattr inside %r %s" % (f.id, qual, where))
            elif fname.startswith("np.") and fname[3:] in _NP_PULLS:
                msg = ("%s() pulls device data to host inside %r %s"
                       % (fname, qual, where))
            elif fname == "jax.device_get":
                msg = "jax.device_get() inside %r %s" % (qual, where)
            if msg is None:
                continue
            key = (node.ctx.relpath, call.lineno, call.col_offset)
            if key in seen:
                continue
            seen.add(key)
            out.append(self.violation(node.ctx, call, msg))
        return out


def _own_calls_within(body: ast.AST, stmt: ast.AST):
    """Calls inside `stmt` that belong to `body`'s node (no nested defs)."""
    own = {id(c) for c in _own_calls(body)}
    stack = [stmt]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n is not stmt:
            continue
        if isinstance(n, ast.Call) and id(n) in own:
            yield n
        stack.extend(ast.iter_child_nodes(n))
