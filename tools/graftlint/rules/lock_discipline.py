"""R13 lock-discipline: acquisition-order cycles and blocking work under
a held lock, checked over the threaded serving/telemetry/tracing/
streaming layer.

PR 11 established the convention by hand: the breaker records a pending
flight-dump under its lock and writes the file *after* releasing
(`_maybe_dump`), the registry parses model files outside `_lock`, the
batcher wakes waiters only through its own Condition. Until now those
were comments. This pass makes them checked invariants:

* **lock-discipline** (primary): any blocking operation reached while a
  lock is held — device dispatch (a call whose target is jit-wrapped),
  ``block_until_ready``, ``np.asarray`` on a value produced by a device
  dispatch in the same function, file I/O (``open``/``os.makedirs``/
  ``os.replace``/``shutil``), ``time.sleep``, and ``Event.wait``.
  Blocking-ness propagates bottom-up over the whole-package call graph,
  so ``push_rows -> observe -> dump_flight`` is caught even though the
  ``open`` lives two modules away; the finding anchors at the call made
  under the lock and names the chain.
* **lock-order-cycle**: the acquisition-order graph (with-statements and
  acquire/release, nested directly or through calls) must be acyclic;
  re-acquiring a non-reentrant lock is the one-node cycle.

Policy exemptions, each load-bearing and documented in docs/LINTING.md:
``telemetry.emit`` (amortized — it flushes its JSONL once per 512 events
and is called on hot paths by design), the checkpoint atomic writers
(``atomic_write_text``/``atomic_write_bytes``/``atomic_open`` — bounded,
fsync-free by default, and the sanctioned way to touch the filesystem),
and ``Condition.wait`` on a condition constructed over the lock being
held (that is what conditions are for; the registry of
``threading.Condition(self._lock)`` associations is built from the same
scan that finds the locks). Unresolvable calls contribute nothing —
consistent with the call graph's may-call conservatism, the rule flags
only what it can prove.

Locks are discovered in the scoped files only (``serving/``,
``streaming/``, ``telemetry.py``, ``tracing.py``); blocking effects are
computed package-wide so a scoped lock region calling into ``ops/`` is
still seen dispatching.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..callgraph import CallGraph, Edge, Node, _own_calls, get_callgraph
from ..core import Package, Violation, dotted_name
from .base import Rule

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

# quals (module, bare name) treated as non-blocking by documented policy
_POLICY_NONBLOCKING = {
    ("telemetry", "emit"), ("telemetry", "TelemetrySession.emit"),
    ("checkpoint", "atomic_open"), ("checkpoint", "_atomic_write"),
    ("checkpoint", "atomic_write_text"), ("checkpoint", "atomic_write_bytes"),
}
_IO_CALLS = {"open", "makedirs", "replace", "rename", "remove", "unlink",
             "fsync", "copyfile", "rmtree", "move"}
_SLEEP_CALLS = {"sleep"}


def _exempt(node: Optional[Node]) -> bool:
    if node is None:
        return False
    return (node.module, node.qual.split(":", 1)[-1]) in _POLICY_NONBLOCKING


class _LockTable:
    """Lock identities discovered in the scoped files.

    Keys: ``module:Class.attr`` for ``self.attr = threading.Lock()``
    assignments, ``module:name`` for module-level locks. Conditions record
    the lock they wrap (their first constructor argument) so waits on
    them are exempt while that lock is held.
    """

    def __init__(self) -> None:
        self.kinds: Dict[str, str] = {}       # key -> lock|rlock|condition
        self.cond_lock: Dict[str, str] = {}   # condition key -> lock key

    def scan(self, ctx, module: str) -> None:
        def ctor_kind(value: ast.AST) -> Optional[str]:
            if not isinstance(value, ast.Call):
                return None
            return _LOCK_CTORS.get(dotted_name(value.func).rsplit(".", 1)[-1])

        def register(target: ast.AST, value: ast.Call, cls: Optional[str],
                     kind: str) -> Optional[str]:
            key = self._key_of(target, module, cls)
            if key is None:
                return None
            self.kinds[key] = kind
            if kind == "condition" and value.args:
                wrapped = self._key_of(value.args[0], module, cls)
                if wrapped is not None:
                    self.cond_lock[key] = wrapped
            return key

        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                kind = ctor_kind(stmt.value)
                if kind:
                    for tgt in stmt.targets:
                        register(tgt, stmt.value, None, kind)
            elif isinstance(stmt, ast.ClassDef):
                for fn in ast.walk(stmt):
                    if not isinstance(fn, _DEFS):
                        continue
                    for sub in ast.walk(fn):
                        if isinstance(sub, ast.Assign):
                            kind = ctor_kind(sub.value)
                            if kind:
                                for tgt in sub.targets:
                                    register(tgt, sub.value, stmt.name, kind)

    def _key_of(self, expr: ast.AST, module: str,
                cls: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None:
            return "%s:%s.%s" % (module, cls, expr.attr)
        if isinstance(expr, ast.Name):
            return "%s:%s" % (module, expr.id)
        return None

    def resolve(self, expr: ast.AST, node: Node) -> Optional[str]:
        """Lock key for a use site (`with self._lock:`), or None."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and node.cls is not None:
            key = "%s:%s.%s" % (node.module, node.cls, expr.attr)
            return key if key in self.kinds else None
        if isinstance(expr, ast.Name):
            key = "%s:%s" % (node.module, expr.id)
            return key if key in self.kinds else None
        return None


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    code = "R13"
    description = ("blocking operation (device dispatch, file I/O, "
                   "Event.wait, sleep) under a held lock, or a cycle in "
                   "the lock acquisition order")
    scope_prefixes = ("serving/", "streaming/")
    scope_exact = ("telemetry.py", "tracing.py")
    whole_program = True

    def check(self, pkg: Package) -> Iterable[Violation]:
        graph = get_callgraph(pkg)
        scoped_ctxs = list(self.scoped(pkg))
        scoped = {id(c) for c in scoped_ctxs}

        locks = _LockTable()
        for ctx in scoped_ctxs:
            mod = graph_module(ctx)
            locks.scan(ctx, mod)

        blocking = self._blocking_effects(graph)
        acquires = self._acquire_summaries(graph, locks, scoped)

        out: List[Violation] = []
        # lock key -> {next lock key -> (ctx, line)}: acquisition order
        order: Dict[str, Dict[str, Tuple[object, int]]] = {}
        seen: Set[Tuple[str, int, str]] = set()

        for qual in sorted(graph.nodes):
            node = graph.nodes[qual]
            if node.node is None or id(node.ctx) not in scoped:
                continue
            self._scan_regions(node, graph, locks, blocking, acquires,
                               order, out, seen)

        out.extend(self._report_cycles(order, locks))
        return out

    # -- blocking-effect fixpoint over the whole package -----------------
    def _blocking_effects(self, graph: CallGraph) -> Dict[str, str]:
        jit_seeds = graph.jit_seeds()
        blocking: Dict[str, str] = {}

        for qual, node in graph.nodes.items():
            if _exempt(node):
                continue
            body = node.node if node.node is not None else node.ctx.tree
            if body is None:
                continue
            reason = self._direct_blocking(node, graph, jit_seeds, body)
            if reason:
                blocking[qual] = "%s at %s:%d" % (
                    reason[0], node.ctx.relpath, reason[1])

        changed, guard = True, 0
        while changed and guard < 200:
            changed = False
            guard += 1
            for qual, node in graph.nodes.items():
                if qual in blocking or _exempt(node):
                    continue
                for e in node.edges:
                    if e.kind == "wrap" or e.target is None:
                        continue
                    if e.target in blocking \
                            and not _exempt(graph.nodes.get(e.target)):
                        blocking[qual] = "%s (via %s)" % (
                            blocking[e.target].split(" (via ")[0], e.target)
                        changed = True
                        break
        return blocking

    def _direct_blocking(self, node: Node, graph: CallGraph,
                         jit_seeds: Set[str], body: ast.AST
                         ) -> Optional[Tuple[str, int]]:
        for call in _own_calls(body):
            name = dotted_name(call.func)
            last = name.rsplit(".", 1)[-1]
            if name == "open" or (last in _IO_CALLS
                                  and name.split(".")[0] in ("os", "shutil")):
                return ("file I/O (%s)" % name, call.lineno)
            if last in _SLEEP_CALLS and name.split(".")[0] == "time":
                return ("time.sleep", call.lineno)
            if last == "block_until_ready":
                return ("block_until_ready device sync", call.lineno)
            if _stream_decode_arg(call) is not None:
                return ("blocking stream decode (np.frombuffer on .%s)"
                        % _stream_decode_arg(call), call.lineno)
            if last in ("device_put", "device_get") \
                    or name.split(".")[0] == "jnp" \
                    or name.startswith("jax.numpy"):
                return ("device op (%s)" % name, call.lineno)
            for ref in graph.resolve_call(node, call):
                if ref.jit_wrapped or (ref.target in jit_seeds):
                    return ("jitted dispatch (%s)" % (name or "<call>"),
                            call.lineno)
        return None

    # -- transitive lock acquisitions per function -----------------------
    def _acquire_summaries(self, graph: CallGraph, locks: _LockTable,
                           scoped: Set[int]) -> Dict[str, Set[str]]:
        acquires: Dict[str, Set[str]] = {}
        for qual, node in graph.nodes.items():
            if node.node is None or id(node.ctx) not in scoped:
                continue
            own: Set[str] = set()
            for stmt in ast.walk(node.node):
                if isinstance(stmt, _DEFS) and stmt is not node.node:
                    continue
                if isinstance(stmt, ast.With):
                    for item in stmt.items:
                        key = locks.resolve(item.context_expr, node)
                        if key:
                            own.add(key)
                elif isinstance(stmt, ast.Call) \
                        and isinstance(stmt.func, ast.Attribute) \
                        and stmt.func.attr == "acquire":
                    key = locks.resolve(stmt.func.value, node)
                    if key:
                        own.add(key)
            if own:
                acquires[qual] = own

        changed, guard = True, 0
        while changed and guard < 200:
            changed = False
            guard += 1
            for qual, node in graph.nodes.items():
                for e in node.edges:
                    if e.kind == "wrap" or e.target is None:
                        continue
                    extra = acquires.get(e.target, set()) \
                        - acquires.get(qual, set())
                    if extra:
                        acquires.setdefault(qual, set()).update(extra)
                        changed = True
        return acquires

    # -- region walk: held-lock tracking + violations --------------------
    def _scan_regions(self, node: Node, graph: CallGraph, locks: _LockTable,
                      blocking: Dict[str, str], acquires: Dict[str, Set[str]],
                      order: Dict[str, Dict[str, Tuple[object, int]]],
                      out: List[Violation],
                      seen: Set[Tuple[str, int, str]]) -> None:
        by_call: Dict[int, List[Edge]] = {}
        for e in node.edges:
            if e.call is not None:
                by_call.setdefault(id(e.call), []).append(e)
        jit_seeds = graph.jit_seeds()
        # names assigned from a device dispatch in this function, for the
        # np.asarray-on-device-array check
        device_names: Set[str] = set()
        for stmt in ast.walk(node.node):
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call):
                for e in by_call.get(id(stmt.value), ()):
                    tgt = graph.nodes.get(e.target) if e.target else None
                    if e.target in jit_seeds \
                            or (tgt is not None and tgt.jitted):
                        for t in stmt.targets:
                            for n in ast.walk(t):
                                if isinstance(n, ast.Name):
                                    device_names.add(n.id)

        def note_order(held: List[str], key: str, line: int) -> None:
            for h in held:
                if h == key:
                    continue
                order.setdefault(h, {}).setdefault(key, (node.ctx, line))

        def check_stmt(st: ast.AST, held: List[str]) -> None:
            """Blocking markers in one statement's own expressions."""
            for call in _calls_in_stmt(st):
                name = dotted_name(call.func)
                last = name.rsplit(".", 1)[-1]
                hit: Optional[str] = None
                if last == "wait" and isinstance(call.func, ast.Attribute):
                    key = locks.resolve(call.func.value, node)
                    wrapped = locks.cond_lock.get(key or "")
                    if key is not None and (key in held or wrapped in held):
                        continue  # Condition.wait over the held lock
                    hit = "%s() blocks while %s is held" % (
                        name, held[-1])
                elif name == "open" \
                        or (last in _IO_CALLS
                            and name.split(".")[0] in ("os", "shutil")):
                    hit = "file I/O (%s) under %s" % (name, held[-1])
                elif last in _SLEEP_CALLS and name.split(".")[0] == "time":
                    hit = "time.sleep under %s" % held[-1]
                elif last == "block_until_ready":
                    hit = "block_until_ready under %s" % held[-1]
                elif last in ("asarray", "ascontiguousarray") \
                        and call.args \
                        and isinstance(call.args[0], ast.Name) \
                        and call.args[0].id in device_names:
                    hit = ("np.%s on a device array pulls it to host "
                           "under %s" % (last, held[-1]))
                elif _stream_decode_arg(call) is not None:
                    hit = ("np.frombuffer decodes a blocking stream read "
                           "(.%s) under %s — drain the stream before "
                           "taking the lock" % (_stream_decode_arg(call),
                                                held[-1]))
                elif last in ("device_put", "device_get") \
                        or name.split(".")[0] == "jnp":
                    hit = "device op (%s) under %s" % (name, held[-1])
                else:
                    for e in by_call.get(id(call), ()):
                        if e.target is None or e.kind == "wrap":
                            continue
                        tgt = graph.nodes.get(e.target)
                        if _exempt(tgt):
                            continue
                        for lk in acquires.get(e.target, ()):
                            note_order(held, lk, call.lineno)
                        if e.target in jit_seeds:
                            hit = ("jitted dispatch %s under %s"
                                   % (name or e.target, held[-1]))
                            break
                        if e.target in blocking:
                            hit = ("call to %s blocks under %s: %s"
                                   % (name or e.target, held[-1],
                                      blocking[e.target]))
                            break
                if hit:
                    dkey = (node.ctx.relpath, call.lineno, held[-1])
                    if dkey not in seen:
                        seen.add(dkey)
                        out.append(self.violation(
                            node.ctx, call,
                            hit + " — hoist the blocking work out of the "
                            "lock scope (record under the lock, act after "
                            "release) or suppress with the bound"))

        def walk(stmts: Sequence[ast.AST], held: List[str]) -> None:
            held = list(held)
            for st in stmts:
                if isinstance(st, _DEFS):
                    continue
                if isinstance(st, ast.With):
                    inner = list(held)
                    for item in st.items:
                        key = locks.resolve(item.context_expr, node)
                        if key is not None:
                            if key in inner \
                                    and locks.kinds.get(key) != "rlock":
                                order.setdefault(key, {}).setdefault(
                                    key, (node.ctx, st.lineno))
                            note_order(inner, key, st.lineno)
                            inner.append(key)
                        elif held:
                            check_stmt(item.context_expr, held)
                    walk(st.body, inner)
                    continue
                if isinstance(st, ast.Expr) \
                        and isinstance(st.value, ast.Call) \
                        and isinstance(st.value.func, ast.Attribute):
                    attr = st.value.func.attr
                    key = locks.resolve(st.value.func.value, node)
                    if key is not None and attr == "acquire":
                        note_order(held, key, st.lineno)
                        held.append(key)
                        continue
                    if key is not None and attr == "release":
                        if key in held:
                            held.remove(key)
                        continue
                if held:
                    check_stmt(st, held)
                for sub in (getattr(st, "body", ()),
                            getattr(st, "orelse", ()),
                            getattr(st, "finalbody", ())):
                    if sub:
                        walk(sub, held)
                for h in getattr(st, "handlers", ()):
                    walk(h.body, held)

        walk(node.node.body, [])

    # -- acquisition-order cycles ----------------------------------------
    def _report_cycles(self, order: Dict[str, Dict[str, Tuple[object, int]]],
                       locks: _LockTable) -> List[Violation]:
        def reaches(src: str, dst: str) -> bool:
            stack, visited = [src], set()
            while stack:
                cur = stack.pop()
                if cur == dst:
                    return True
                if cur in visited:
                    continue
                visited.add(cur)
                stack.extend(order.get(cur, ()))
            return False

        out: List[Violation] = []
        for a in sorted(order):
            for b in sorted(order[a]):
                ctx, line = order[a][b]
                if a == b:
                    out.append(Violation(
                        "lock-order-cycle", self.code, ctx.relpath, line, 0,
                        "non-reentrant lock %s is re-acquired while "
                        "already held: self-deadlock (use an RLock or "
                        "split the critical section)" % a))
                    continue
                if reaches(b, a):
                    out.append(Violation(
                        "lock-order-cycle", self.code, ctx.relpath, line, 0,
                        "acquiring %s while holding %s completes an "
                        "acquisition-order cycle (%s is also taken while "
                        "%s is held elsewhere): two threads interleaving "
                        "these orders deadlock — pick one global order"
                        % (b, a, a, b)))
        return out


def _stream_decode_arg(call: ast.Call) -> Optional[str]:
    """The stream method name when this call is
    ``np.frombuffer(<x>.read(...))`` / ``.recv(...)`` — a zero-copy decode
    whose SOURCE is a blocking socket/file read, so holding a lock across
    it stalls every waiter on the peer's send pace; else None."""
    if dotted_name(call.func).rsplit(".", 1)[-1] != "frombuffer":
        return None
    if not call.args or not isinstance(call.args[0], ast.Call):
        return None
    src = call.args[0].func
    if isinstance(src, ast.Attribute) and src.attr in ("read", "recv",
                                                       "recv_into"):
        return src.attr
    return None


def graph_module(ctx) -> str:
    from ..callgraph import module_name

    return module_name(ctx.relpath)


def _calls_in_stmt(st: ast.AST):
    """Call nodes in one statement, skipping nested defs/lambdas and the
    bodies of nested compound statements (walked separately)."""
    blocked: Set[int] = set()
    for field in ("body", "orelse", "finalbody", "handlers"):
        for sub in getattr(st, field, ()):
            blocked.add(id(sub))
    stack = [st]
    while stack:
        n = stack.pop()
        if id(n) in blocked or isinstance(n, _DEFS) \
                or isinstance(n, ast.Lambda):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))
