"""R3 Pallas kernel rules: tile alignment, prefetch arity, host-op bans.

Three checks over every file that mentions `pl.pallas_call` (the rule
self-scopes — any module growing a kernel is covered automatically):

pallas-tile-shape
    Literal integer dims in `pl.BlockSpec` block shapes must respect the
    Mosaic f32 tile: last dim a multiple of 128, second-to-last a
    multiple of 8 (guides/pallas_guide.md "Tiling Constraints"). A dim
    of literal 1 is allowed (squeeze dims — e.g. the [tile_rows, 1] slot
    column — lower fine). Symbolic dims are skipped: the module-level
    constants they name (DEFAULT_TILE_ROWS=1024, COMPACT_TILE=512) are
    resolved when they are plain `NAME = <int>` assignments in the same
    file, so renaming a constant to an unaligned value still trips the
    gate. Misaligned blocks don't fail under interpret-mode tests — they
    fail on real hardware, which is exactly why a static check earns its
    keep.

pallas-prefetch-arity
    With `PrefetchScalarGridSpec(num_scalar_prefetch=k, grid=<len-g>)`,
    every index_map lambda must take g + k parameters (grid indices
    first, then the scalar-prefetch refs). Getting this wrong reorders
    which operand the kernel sees as scalar prefetch — the bug class the
    ragged histogram's indirection tables would silently shift into.
    Plain `pallas_call(grid=...)` index_maps must take g parameters.

pallas-host-op
    Kernel bodies (the callable handed to pallas_call, resolved through
    one level of `_make_kernel(...)`-style factories) must not call numpy,
    print, `.item()`, host callbacks, or data-dependent-shape jnp ops
    (nonzero/unique) — none of these lower through Mosaic.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional

from ..core import Package, Violation, dotted_name, keyword_arg, literal_int
from .base import Rule

_KERNEL_BANNED_JNP = {"nonzero", "unique", "save", "load", "unpackbits",
                      "packbits", "asarray"}
_KERNEL_BANNED_METHODS = {"item", "tolist", "block_until_ready"}
_KERNEL_BANNED_DOTTED = {"jax.device_get", "jax.device_put",
                         "jax.pure_callback", "jax.experimental.io_callback",
                         "jax.debug.callback"}


def _module_int_constants(tree: ast.Module) -> Dict[str, int]:
    """NAME = <int literal> assignments at module level."""
    out: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            val = literal_int(node.value)
            if val is not None:
                out[node.targets[0].id] = val
    return out


class PallasRule(Rule):
    name = "pallas-tile-shape"  # primary id; subchecks carry their own
    code = "R3"
    description = ("Pallas invariants: (8, 128) block alignment, "
                   "scalar-prefetch index_map arity, no host ops in kernels")

    def check(self, pkg: Package) -> Iterable[Violation]:
        out: List[Violation] = []
        for ctx in self.scoped(pkg):
            if "pallas_call" not in ctx.source:
                continue
            consts = _module_int_constants(ctx.tree)
            out.extend(self._check_block_shapes(ctx, consts))
            out.extend(self._check_prefetch_arity(ctx))
            out.extend(self._check_kernel_bodies(ctx))
        return out

    # -- tile alignment --------------------------------------------------
    def _check_block_shapes(self, ctx, consts: Dict[str, int]) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and dotted_name(node.func).endswith("BlockSpec")):
                continue
            shape = keyword_arg(node, "block_shape")
            if shape is None and node.args:
                shape = node.args[0]
            if not isinstance(shape, ast.Tuple) or len(shape.elts) < 2:
                continue  # 1-D / symbolic whole-shape blocks: nothing to check

            def resolve(el: ast.AST) -> Optional[int]:
                v = literal_int(el)
                if v is None and isinstance(el, ast.Name):
                    v = consts.get(el.id)
                return v

            last = resolve(shape.elts[-1])
            sublane = resolve(shape.elts[-2])
            if last is not None and last != 1 and last % 128 != 0:
                out.append(self.violation(
                    ctx, shape, "BlockSpec last dim %d is not a multiple "
                    "of 128 (Mosaic lane tile)" % last))
            if sublane is not None and sublane != 1 and sublane % 8 != 0:
                out.append(self.violation(
                    ctx, shape, "BlockSpec second-to-last dim %d is not a "
                    "multiple of 8 (Mosaic sublane tile)" % sublane))
        return out

    # -- scalar-prefetch arity -------------------------------------------
    def _grid_len(self, call: ast.Call) -> Optional[int]:
        grid = keyword_arg(call, "grid")
        if grid is None:
            return None
        if isinstance(grid, ast.Tuple):
            return len(grid.elts)
        return 1  # grid=<scalar expr>

    def _index_maps(self, call: ast.Call):
        """(lambda, spec_kind) for every index_map in in_specs/out_specs."""
        for kind in ("in_specs", "out_specs"):
            specs = keyword_arg(call, kind)
            if specs is None:
                continue
            elts = specs.elts if isinstance(specs, (ast.List, ast.Tuple)) \
                else [specs]
            for spec in elts:
                if not isinstance(spec, ast.Call):
                    continue
                lam = keyword_arg(spec, "index_map")
                if lam is None and len(spec.args) >= 2:
                    lam = spec.args[1]
                if isinstance(lam, ast.Lambda):
                    yield lam, kind

    def _check_prefetch_arity(self, ctx) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname.endswith("PrefetchScalarGridSpec"):
                nsp_node = keyword_arg(node, "num_scalar_prefetch")
                nsp = literal_int(nsp_node) if nsp_node is not None else None
                glen = self._grid_len(node)
                if nsp is None or glen is None:
                    continue
                want = glen + nsp
                label = ("%d grid indices + %d scalar-prefetch refs"
                         % (glen, nsp))
            elif fname.endswith("pallas_call"):
                glen = self._grid_len(node)
                if glen is None:
                    continue
                want = glen
                label = "%d grid indices" % glen
            else:
                continue
            for lam, kind in self._index_maps(node):
                got = len(lam.args.args) + len(lam.args.posonlyargs)
                if got != want:
                    out.append(self.violation(
                        ctx, lam, "%s index_map takes %d args, expected %d "
                        "(%s) — scalar-prefetch operands come first and "
                        "shift every index_map signature" % (
                            kind, got, want, label),
                        rule="pallas-prefetch-arity"))
        return out

    # -- host ops inside kernel bodies -----------------------------------
    def _kernel_defs(self, ctx) -> List[ast.FunctionDef]:
        """Kernels = first positional arg of pallas_call: a Name bound to a
        def in this module, or a call to a factory whose returned inner def
        is the kernel."""
        defs: Dict[str, ast.FunctionDef] = {
            n.name: n for n in ast.walk(ctx.tree)
            if isinstance(n, ast.FunctionDef)}
        kernels: List[ast.FunctionDef] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and dotted_name(node.func).endswith("pallas_call")
                    and node.args):
                continue
            first = node.args[0]
            if isinstance(first, ast.Name) and first.id in defs:
                kernels.append(defs[first.id])
            elif isinstance(first, ast.Call):
                fac = dotted_name(first.func).rsplit(".", 1)[-1]
                factory = defs.get(fac)
                if factory is None:
                    continue
                returned = {n.value.id for n in ast.walk(factory)
                            if isinstance(n, ast.Return)
                            and isinstance(n.value, ast.Name)}
                for inner in ast.walk(factory):
                    if isinstance(inner, ast.FunctionDef) \
                            and inner.name in returned:
                        kernels.append(inner)
        return kernels

    def _check_kernel_bodies(self, ctx) -> List[Violation]:
        out: List[Violation] = []
        for kern in self._kernel_defs(ctx):
            for node in ast.walk(kern):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                fname = dotted_name(f)
                msg = None
                if fname.startswith("np."):
                    msg = "numpy call %s() in Pallas kernel %r" % (
                        fname, kern.name)
                elif isinstance(f, ast.Name) and f.id == "print":
                    msg = ("print() in Pallas kernel %r (use "
                           "pl.debug_print)" % kern.name)
                elif isinstance(f, ast.Attribute) \
                        and f.attr in _KERNEL_BANNED_METHODS:
                    msg = ".%s() in Pallas kernel %r" % (f.attr, kern.name)
                elif fname in _KERNEL_BANNED_DOTTED:
                    msg = "%s() in Pallas kernel %r" % (fname, kern.name)
                elif fname.startswith("jnp.") \
                        and fname[4:] in _KERNEL_BANNED_JNP:
                    msg = ("%s() in Pallas kernel %r does not lower "
                           "through Mosaic" % (fname, kern.name))
                if msg:
                    out.append(self.violation(ctx, node, msg,
                                              rule="pallas-host-op"))
        return out
