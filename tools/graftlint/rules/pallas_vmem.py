"""R14 pallas-vmem: every pallas_call's worst-case block footprint must
fit the VMEM capacity floor from perfmodel.py.

Mosaic keeps a kernel's live blocks — every in_spec and out_spec block,
double-buffered so the next grid step's DMA overlaps compute — resident
in VMEM. A BlockSpec that grew past the budget fails at *lowering time on
the device*, which for this repo means during a bench run on hardware CI
never sees. This rule evaluates the failure statically:

    footprint = 2 * sum(prod(block_shape) * dtype_bytes per spec)

Block dimensions resolve through the same chain R3 uses — integer
literals, module constants, function-local ``NAME = <int>`` assignments —
extended with keyword/positional parameter *defaults* (the static-argnum
tile sizes) and constant folding of ``+ - * // **``. A dimension that
stays symbolic (a runtime shape like ``Gp`` or ``F``) is replaced by its
entry in ``perfmodel.PALLAS_DIM_BOUNDS``: the lint-time cap the call
sites are certified against. Unknown names fall back to a conservative
256. Element size defaults to 4 bytes (int8 planes are thus over-counted,
never under).

The budget and the bounds table live in ``<root>/perfmodel.py`` and are
read from its AST (literal extraction + the same constant folding — the
linter stays stdlib-only, nothing is imported). Packages without a
perfmodel (the test fixtures) get the built-in 16 MiB floor and the
built-in bounds. Because the tables are rule *configuration*, the cache
key includes the perfmodel digest (cache.py) — editing a bound reruns the
rule everywhere.

Suppression policy: a kernel that genuinely needs more than the floor on
a bigger device must carry a reasoned suppression naming the device kind
it is restricted to — there is no blanket opt-out.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..core import FileContext, Package, Violation, dotted_name, keyword_arg
from .base import Rule
from .pallas_rules import _module_int_constants

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_DEFAULT_BUDGET = 16 * 1024 * 1024
_DEFAULT_BOUND = 256
_BUILTIN_BOUNDS = {"num_bins": 256, "n_bins": 256, "tile_rows": 2048}
_DTYPE_BYTES = {"float64": 8, "int64": 8, "uint64": 8,
                "float32": 4, "int32": 4, "uint32": 4,
                "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
                "int8": 1, "uint8": 1, "bool_": 1, "bool": 1}


def _fold_int(node: ast.AST, resolve) -> Optional[int]:
    """Constant-fold an int expression; `resolve(name)` supplies values
    for bare names (module consts, locals, bounds)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _fold_int(node.operand, resolve)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        left = _fold_int(node.left, resolve)
        right = _fold_int(node.right, resolve)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.FloorDiv) and right != 0:
            return left // right
        if isinstance(node.op, ast.Pow) and 0 <= right <= 64:
            return left ** right
        return None
    if isinstance(node, (ast.Name, ast.Attribute)):
        return resolve(node)
    return None


def _perfmodel_tables(pkg: Package) -> Tuple[int, Dict[str, int]]:
    """(budget floor, dim bounds) extracted from <root>/perfmodel.py's
    AST, or the built-in defaults when the package has none."""
    budget, bounds = _DEFAULT_BUDGET, dict(_BUILTIN_BOUNDS)
    path = pkg.root / "perfmodel.py"
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return budget, bounds
    consts = _module_int_constants(tree)

    def resolve(node: ast.AST) -> Optional[int]:
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        return None

    for stmt in tree.body:
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets: Sequence[ast.AST] = [stmt.target]
            value = stmt.value
        elif isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        else:
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if "PALLAS_VMEM_DEFAULT_BYTES" in names:
            v = _fold_int(value, resolve)
            if v is not None:
                budget = v
        if "PALLAS_DIM_BOUNDS" in names and isinstance(value, ast.Tuple):
            for elt in value.elts:
                if isinstance(elt, ast.Tuple) and len(elt.elts) == 2 \
                        and isinstance(elt.elts[0], ast.Constant) \
                        and isinstance(elt.elts[0].value, str):
                    bound = _fold_int(elt.elts[1], resolve)
                    if bound is not None:
                        bounds[elt.elts[0].value] = bound
    return budget, bounds


def _enclosing_function(tree: ast.Module, call: ast.Call
                        ) -> Optional[ast.AST]:
    """Innermost def containing `call` (by position)."""
    best: Optional[ast.AST] = None
    for node in ast.walk(tree):
        if isinstance(node, _DEFS):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= call.lineno <= end:
                if best is None or node.lineno > best.lineno:
                    best = node
    return best


def _local_env(fn: Optional[ast.AST], consts: Dict[str, int]
               ) -> Tuple[Dict[str, int], Dict[str, ast.AST]]:
    """(resolvable ints, name -> assigned expr) inside one function:
    local int assignments plus parameter defaults."""
    ints: Dict[str, int] = {}
    assigns: Dict[str, ast.AST] = {}
    if fn is None:
        return ints, assigns

    def resolve(node: ast.AST) -> Optional[int]:
        if isinstance(node, ast.Name):
            return ints.get(node.id, consts.get(node.id))
        return None

    args = fn.args
    pos = list(args.posonlyargs) + list(args.args)
    for arg, default in zip(pos[len(pos) - len(args.defaults):],
                            args.defaults):
        v = _fold_int(default, resolve)
        if v is not None:
            ints[arg.arg] = v
    for arg, default in zip(args.kwonlyargs, args.kw_defaults):
        if default is not None:
            v = _fold_int(default, resolve)
            if v is not None:
                ints[arg.arg] = v
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            assigns[stmt.targets[0].id] = stmt.value
            v = _fold_int(stmt.value, resolve)
            if v is not None:
                ints[stmt.targets[0].id] = v
    return ints, assigns


def _block_shape(spec_call: ast.Call) -> Optional[ast.Tuple]:
    shape = keyword_arg(spec_call, "block_shape")
    if shape is None and spec_call.args:
        shape = spec_call.args[0]
    return shape if isinstance(shape, ast.Tuple) else None


class PallasVmemRule(Rule):
    name = "pallas-vmem"
    code = "R14"
    description = ("worst-case pallas_call block footprint (double-"
                   "buffered) exceeds the VMEM budget floor from "
                   "perfmodel.py")

    def check(self, pkg: Package) -> Iterable[Violation]:
        budget, bounds = _perfmodel_tables(pkg)
        out: List[Violation] = []
        for ctx in self.scoped(pkg):
            if "pallas_call" not in ctx.source:
                continue
            out.extend(self._check_file(ctx, budget, bounds))
        return out

    def _check_file(self, ctx: FileContext, budget: int,
                    bounds: Dict[str, int]) -> List[Violation]:
        consts = _module_int_constants(ctx.tree)
        out: List[Violation] = []
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            if dotted_name(call.func).rsplit(".", 1)[-1] != "pallas_call":
                continue
            fn = _enclosing_function(ctx.tree, call)
            local_ints, local_assigns = _local_env(fn, consts)

            def resolve_name(node: ast.AST) -> Optional[int]:
                if isinstance(node, ast.Name):
                    v = local_ints.get(node.id, consts.get(node.id))
                    if v is not None:
                        return v
                    return bounds.get(node.id, _DEFAULT_BOUND)
                if isinstance(node, ast.Attribute):
                    return bounds.get(node.attr, _DEFAULT_BOUND)
                return None

            specs = self._spec_calls(call, local_assigns)
            total = 0
            parts: List[str] = []
            for spec in specs:
                shape = _block_shape(spec)
                if shape is None:
                    continue
                elems = 1
                dims: List[str] = []
                for d in shape.elts:
                    v = _fold_int(d, resolve_name)
                    if v is None:
                        v = _DEFAULT_BOUND
                    elems *= max(int(v), 1)
                    dims.append(str(v))
                total += elems * 4
                parts.append("(%s)" % ", ".join(dims))
            if not parts:
                continue
            worst = 2 * total  # Mosaic double-buffers the block pipeline
            if worst > budget:
                out.append(self.violation(
                    ctx, call,
                    "worst-case VMEM footprint %.1f MiB (2x double-"
                    "buffered blocks %s at 4 B/elem, runtime dims bounded "
                    "by perfmodel.PALLAS_DIM_BOUNDS) exceeds the %.1f MiB "
                    "device floor (perfmodel.PALLAS_VMEM_DEFAULT_BYTES) — "
                    "shrink the tile, split the grid, or restrict the "
                    "kernel to a larger device with a reasoned "
                    "suppression"
                    % (worst / 1048576.0, " + ".join(parts),
                       budget / 1048576.0)))
        return out

    def _spec_calls(self, call: ast.Call,
                    local_assigns: Dict[str, ast.AST]) -> List[ast.Call]:
        """Every BlockSpec construction feeding this pallas_call: through
        in_specs/out_specs/grid_spec keywords, following one level of
        function-local ``name = <expr>`` indirection."""
        roots: List[ast.AST] = []
        for kw in ("grid_spec", "in_specs", "out_specs"):
            value = keyword_arg(call, kw)
            if isinstance(value, ast.Name):
                value = local_assigns.get(value.id)
            if value is not None:
                roots.append(value)
        if not roots:
            roots = [call]
        specs: List[ast.Call] = []
        seen = set()
        for root in roots:
            for sub in ast.walk(root):
                if isinstance(sub, ast.Call) and id(sub) not in seen \
                        and dotted_name(sub.func).rsplit(".", 1)[-1] \
                        == "BlockSpec":
                    seen.add(id(sub))
                    specs.append(sub)
        return specs
