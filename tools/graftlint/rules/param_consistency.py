"""R4 param-unread: every accepted parameter must be read somewhere.

The defect class PR 1 fixed by hand: `path_smooth` and `monotone_penalty`
were accepted by Config (spec-parity with the reference), silently ignored
by the learner, and the trained model quietly differed from the reference.
Nothing crashes — the worst kind of bug. This rule generalizes the fix:
cross-reference the extracted parameter spec (`_param_spec.py`, the output
of tools/extract_param_spec.py that config.py consumes) against actual
reads across the package, and fail on accepted-but-never-read names.

A "read" is any of:
  * an attribute load `<expr>.<param>` anywhere outside _param_spec.py
    (Config exposes every param as an attribute, so `cfg.num_leaves`,
    `self.config.max_depth`, `config.feature_fraction` all count);
  * `getattr(obj, "<param>", ...)`;
  * the name as a string literal (subscripts like params["metric"],
    warning text that explicitly declares the param ignored — the
    PR 1 pattern of warning loudly IS an acknowledged read).

Intentionally-unread params (reference-parity surface the TPU port will
never use, e.g. gpu_platform_id) carry line suppressions with reasons in
_param_spec.py — visible in the same file that admits them to the API.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from ..core import Package, Violation, dotted_name
from .base import Rule

_SPEC_FILENAME = "_param_spec.py"
_SPEC_VAR = "PARAM_SPEC"


def _spec_entries(tree: ast.Module) -> Dict[str, ast.AST]:
    """param name -> the spec tuple node (for line numbers)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == _SPEC_VAR \
                and isinstance(node.value, (ast.List, ast.Tuple)):
            out: Dict[str, ast.AST] = {}
            for elt in node.value.elts:
                if isinstance(elt, ast.Tuple) and elt.elts \
                        and isinstance(elt.elts[0], ast.Constant) \
                        and isinstance(elt.elts[0].value, str):
                    out[elt.elts[0].value] = elt
            return out
    return {}


def _reads(tree: ast.AST, names: Set[str]) -> Set[str]:
    found: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in names:
            found.add(node.attr)
        elif isinstance(node, ast.Call) and dotted_name(node.func) == "getattr" \
                and len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Constant) \
                and node.args[1].value in names:
            found.add(node.args[1].value)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value in names:
                found.add(node.value)
    return found


class ParamConsistencyRule(Rule):
    name = "param-unread"
    code = "R4"
    description = ("parameter accepted by the spec/config but never read "
                   "anywhere in the package (the path_smooth defect class)")
    whole_program = True  # reads usage across every file in the package

    def check(self, pkg: Package) -> Iterable[Violation]:
        spec_ctx = None
        for ctx in pkg.files:
            if ctx.relpath.endswith(_SPEC_FILENAME) and ctx.tree is not None:
                spec_ctx = ctx
                break
        if spec_ctx is None:
            return []  # nothing to cross-reference (fixture dirs, subtrees)
        entries = _spec_entries(spec_ctx.tree)
        if not entries:
            return []
        names = set(entries)
        read: Set[str] = set()
        for ctx in pkg.files:
            if ctx is spec_ctx or ctx.tree is None:
                continue
            read |= _reads(ctx.tree, names)
            if read == names:
                break
        out: List[Violation] = []
        for name in sorted(names - read):
            out.append(self.violation(
                spec_ctx, entries[name],
                "parameter %r is accepted by the spec but never read by "
                "any module — it will be silently ignored at train time "
                "(read it, warn about it at config time, or suppress here "
                "with the reason it stays surface-only)" % name))
        return out
