"""R9 telemetry-hygiene: hot-path event emission must be guard-gated.

`telemetry.emit()` is a single module-global None-check when no session is
active — but only AFTER its arguments are evaluated. An unguarded

    telemetry.emit("tree_wave", efficiency=committed / speculated, ...)

in a per-wave or per-chunk loop builds the whole payload dict (and any
device syncs hiding in the field expressions) on EVERY trip, telemetry on
or off — exactly the overhead the <1% claim forbids. In the hot-path set
(R5's scope: treelearner/, parallel/, ops/predict.py) every `*.emit(...)`
call on a telemetry object must sit under an `if` whose test references
`enabled` (idiomatically `if telemetry.enabled():`). The always-cheap
counter APIs (`global_timer.add_count` / `set_count`) need no guard and
are the right tool for per-wave integers.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Package, Violation, dotted_name
from .base import Rule


def _test_mentions_enabled(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and "enabled" in node.id:
            return True
        if isinstance(node, ast.Attribute) and "enabled" in node.attr:
            return True
    return False


def _emit_calls_with_guards(tree: ast.AST):
    """Yield (call_node, guarded) for every telemetry-style emit call;
    guarded = an ancestor `if`/ternary whose test references `enabled`."""
    def walk(node: ast.AST, guarded: bool):
        for child in ast.iter_child_nodes(node):
            child_guarded = guarded
            if isinstance(child, ast.If) and _test_mentions_enabled(
                    child.test):
                child_guarded = True
            if isinstance(child, ast.IfExp) and _test_mentions_enabled(
                    child.test):
                child_guarded = True
            if isinstance(child, ast.Call):
                # `from .. import telemetry; telemetry.emit(...)` is the
                # package idiom; a bare aliased `emit(...)` is ambiguous
                # (logging.Handler.emit) — keep the rule conservative
                if dotted_name(child.func).endswith("telemetry.emit"):
                    yield child, child_guarded
            yield from walk(child, child_guarded)
    yield from walk(tree, False)


class TelemetryHygieneRule(Rule):
    name = "telemetry-hygiene"
    code = "R9"
    description = ("telemetry.emit() in a hot-path file outside an "
                   "`if ...enabled...:` guard — payload construction runs "
                   "even with telemetry off (use the counter APIs or guard "
                   "the emission)")
    scope_prefixes = ("treelearner/", "parallel/", "serving/", "streaming/")
    # perfmodel/exposition sit on the scrape path: a /metrics render or a
    # per-dispatch capture hook runs with telemetry off too, so unguarded
    # emits there cost every caller, not just telemetry users. tracing.py
    # is IN scope on purpose: its recorder append (tracing.note) is the
    # one sanctioned unguarded hot-path emit — O(1), allocation-bounded,
    # no I/O — so any telemetry.emit added there must still be guarded.
    scope_exact = ("ops/predict.py", "perfmodel.py", "exposition.py",
                   "tracing.py")

    def check(self, pkg: Package) -> Iterable[Violation]:
        out: List[Violation] = []
        for ctx in self.scoped(pkg):
            for call, guarded in _emit_calls_with_guards(ctx.tree):
                if guarded:
                    continue
                out.append(self.violation(
                    ctx, call,
                    "telemetry.emit() outside an enabled-guard in a "
                    "hot-path file — the event payload is built on every "
                    "call even when telemetry is off; wrap it in "
                    "`if telemetry.enabled():` or publish the figure "
                    "through global_timer.add_count/set_count instead"))
        return out
