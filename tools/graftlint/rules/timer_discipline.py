"""R5 untimed-hot-func: big hot-path functions must feed the global timer.

Perf accounting is only trustworthy when it is complete: the
`device_hist_rows` counter proving the rows-in-leaf wave design is
O(selected rows) lives next to a `global_timer.scope("tree_device")`
wall-clock scope, and a 100-line helper that bypasses both is invisible
in every perf report. Any function of more than 50 source lines in
treelearner/, parallel/, the serving hot path ops/predict.py, or the
fused split-scan ops/scan_pallas.py must reference
`utils.timer.global_timer` (a scope, an add_count, anything) or wear the
`@timed(...)` decorator.

Exemptions, because they are structurally untimeable from the inside:
  * jit-decorated functions — host timers inside a traced body measure
    trace time once, then nothing; the call site owns the scope (that is
    exactly how grow_tree_on_device is accounted, device.py's
    `global_timer.scope("tree_device")`).
  * nested defs — they execute inside their parent's scope.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Package, Violation, dotted_name
from .base import Rule, module_functions
from .jit_boundary import _is_jitted

_MAX_LINES = 50


def _uses_timer(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == "global_timer":
            return True
        if isinstance(node, ast.Attribute) \
                and dotted_name(node).endswith("global_timer"):
            return True
    for dec in getattr(fn, "decorator_list", []):
        name = dotted_name(dec.func if isinstance(dec, ast.Call) else dec)
        if name.endswith("timed"):
            return True
    return False


class TimerDisciplineRule(Rule):
    name = "untimed-hot-func"
    code = "R5"
    description = (">50-line function in treelearner/, parallel/, or "
                   "ops/predict.py without a global_timer scope/counter "
                   "(perf accounting gap)")
    scope_prefixes = ("treelearner/", "parallel/")
    scope_exact = ("ops/predict.py", "ops/scan_pallas.py")

    def check(self, pkg: Package) -> Iterable[Violation]:
        out: List[Violation] = []
        for ctx in self.scoped(pkg):
            for qual, fn in module_functions(ctx.tree):
                span = (fn.end_lineno or fn.lineno) - fn.lineno + 1
                if span <= _MAX_LINES:
                    continue
                if _is_jitted(fn):
                    continue  # traced body; the call site owns the scope
                if _uses_timer(fn):
                    continue
                out.append(self.violation(
                    ctx, fn,
                    "%r spans %d lines with no global_timer scope or "
                    "counter — its cost is invisible to perf reports "
                    "(wrap the hot section, decorate with @timed, or "
                    "suppress with the reason it is cold)" % (qual, span)))
        return out
