"""SARIF 2.1.0 export for graftlint findings.

One run, one tool ("graftlint"), every rule — including driver-level
finding ids like E0/S1 — declared in the driver's rule table so viewers
can resolve ruleId without guessing. Suppressed findings are emitted with
a SARIF `suppressions` entry (`kind: inSource`, the directive's reason as
the justification) rather than dropped: code-scanning UIs then show them
as reviewed, matching the linter's own philosophy that an escape hatch is
a visible artifact, not an omission.

Columns: graftlint's internal `col` is 0-based (ast.col_offset); SARIF
wants 1-based startColumn.
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List

from .core import Violation

_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
           "Schemata/sarif-schema-2.1.0.json")


def _rule_table() -> List[Dict]:
    from .rules import EXTRA_IDS, RULES

    rules: List[Dict] = []
    seen = set()
    for rule in RULES:
        rules.append({
            "id": rule.name,
            "shortDescription": {"text": rule.description},
            "properties": {"code": rule.code},
        })
        seen.add(rule.name)
    for name, code in sorted(EXTRA_IDS.items(), key=lambda kv: kv[1]):
        if name in seen:
            continue
        rules.append({
            "id": name,
            "shortDescription": {
                "text": "driver-level finding (%s)" % code},
            "properties": {"code": code},
        })
    return rules


def _result(v: Violation, uri_prefix: str) -> Dict:
    uri = "%s/%s" % (uri_prefix.rstrip("/"), v.path) if uri_prefix else v.path
    res: Dict = {
        "ruleId": v.rule,
        "level": "error",
        "message": {"text": v.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": uri},
                "region": {"startLine": v.line,
                           "startColumn": v.col + 1},
            },
        }],
    }
    if v.suppressed:
        res["level"] = "note"
        res["suppressions"] = [{
            "kind": "inSource",
            "justification": v.reason,
        }]
    return res


def to_sarif(violations: Iterable[Violation],
             suppressed: Iterable[Violation] = (),
             uri_prefix: str = "") -> Dict:
    """Build the SARIF document (a plain dict; `render_sarif` serializes).

    `uri_prefix` re-roots the package-relative finding paths for the
    consumer — CI passes the linted directory ("lightgbm_tpu") so upload
    artifacts resolve against the repository root.
    """
    return {
        "$schema": _SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "rules": _rule_table(),
            }},
            "results": [_result(v, uri_prefix) for v in violations]
                       + [_result(v, uri_prefix) for v in suppressed],
        }],
    }


def render_sarif(violations: Iterable[Violation],
                 suppressed: Iterable[Violation] = (),
                 uri_prefix: str = "") -> str:
    return json.dumps(to_sarif(violations, suppressed, uri_prefix),
                      indent=2, sort_keys=True)
