#!/usr/bin/env python3
"""perfreport: human view of a bench record's cost-model attribution.

bench.py embeds the perfmodel.attribution() report (per-stage measured
wall, fraction of the training wall, analytic model bytes, model-implied
seconds at peak bandwidth, measured-vs-model drift, roofline fraction,
and XLA's static cost_analysis per captured dispatch) in every capture
record. This renders it as a table:

    python tools/perfreport.py BENCH_LEDGER.jsonl      # newest record
    python tools/perfreport.py record.json
    python tools/perfreport.py BENCH_LEDGER.jsonl --index -2

stdlib only.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def _load(path: str) -> List[Dict[str, Any]]:
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read().strip()
    if path.endswith(".jsonl"):
        return [json.loads(ln) for ln in text.splitlines() if ln.strip()]
    obj = json.loads(text)
    return obj if isinstance(obj, list) else [obj]


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:,.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def render(record: Dict[str, Any]) -> str:
    lines: List[str] = []
    fp = record.get("fingerprint") or {}
    lines.append(
        f"perfreport: {record.get('metric', '?')} = {record.get('value', '?')}"
        f" {record.get('unit', '')}  (sha {fp.get('git_sha', '?')}, "
        f"{record.get('platform', '?')}/{fp.get('device_kind', '?')}, "
        f"rows {record.get('rows', '?')}, iters {record.get('iters', '?')})")
    attr = record.get("attribution")
    if not isinstance(attr, dict):
        lines.append("  no attribution block in this record "
                     "(pre-schema-v1 capture?)")
        return "\n".join(lines)
    lines.append(f"  training wall {attr.get('total_s', '?')}s, "
                 f"stage-covered {attr.get('covered_s', '?')}s, "
                 f"fractions_sum {attr.get('fractions_sum', '?')}")
    bw = attr.get("peak_bw_bytes_per_s")
    if bw:
        lines.append(f"  roofline bandwidth {_fmt_bytes(bw)}/s "
                     "(LGBM_TPU_PEAK_BW_GBPS to calibrate)")
    header = (f"  {'stage':<14}{'wall_s':>10}{'frac':>8}{'model':>12}"
              f"{'model_s':>10}{'drift':>9}{'roofline':>10}")
    lines.append(header)
    lines.append("  " + "-" * (len(header) - 2))
    stages = attr.get("stages", {})
    for name, st in sorted(stages.items(),
                           key=lambda kv: -kv[1].get("wall_s", 0.0)):
        model_s = (f"{st['model_s']:>10.4f}" if "model_s" in st
                   else f"{'-':>10}")
        drift = (f"{st['drift_pct']:>+8.1f}%" if "drift_pct" in st
                 else f"{'-':>9}")
        roof = (f"{st['roofline_frac']:>10.1%}" if "roofline_frac" in st
                else f"{'-':>10}")
        lines.append(f"  {name:<14}"
                     f"{st.get('wall_s', 0.0):>10.4f}"
                     f"{st.get('fraction', 0.0):>8.1%}"
                     f"{_fmt_bytes(st.get('model_bytes')):>12}"
                     f"{model_s}{drift}{roof}")
        comp = st.get("model_components_bytes")
        if comp:
            inner = ", ".join(f"{k}={_fmt_bytes(v)}"
                              for k, v in sorted(comp.items()))
            lines.append(f"    model components: {inner}")
    static = attr.get("static")
    if static:
        lines.append("  static cost_analysis (per captured dispatch):")
        for stage, entry in sorted(static.items()):
            if "error" in entry:
                lines.append(f"    {stage:<12} unavailable: {entry['error']}")
                continue
            lines.append(
                f"    {stage:<12} flops={entry.get('flops', 0):,.0f}  "
                f"bytes={_fmt_bytes(entry.get('bytes_accessed'))}  "
                f"args={_fmt_bytes(entry.get('argument_bytes'))}  "
                f"out={_fmt_bytes(entry.get('output_bytes'))}  "
                f"temp={_fmt_bytes(entry.get('temp_bytes'))}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render a bench record's cost-model attribution")
    ap.add_argument("path", help="BENCH_LEDGER.jsonl or a record .json")
    ap.add_argument("--index", type=int, default=-1,
                    help="which ledger record (default -1 = newest)")
    args = ap.parse_args(argv)
    records = _load(args.path)
    if not records:
        print(f"perfreport: {args.path} is empty", file=sys.stderr)
        return 1
    print(render(records[args.index]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
