"""CI serving smoke: boot the hardened prediction server, drive it over
HTTP with concurrent clients — including a corrupt upload, a
deadline-expired request, and a fault-injected breaker flap — and assert
the service stays healthy and bit-exact throughout.

    python tools/serve_smoke.py [telemetry_dir]

Exits nonzero on any violated invariant. When a telemetry dir is given the
run records a full event stream there (validate it afterwards with
`python tools/teldiff.py --self-check <dir>`). Flight-recorder dumps land
in the same dir (a temp dir otherwise): the breaker-open scenario proves
the auto-dump end to end — dump present, OPEN transition + preceding
events inside, flightview renders it, teldiff accepts the format.
"""
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _call(port, path, payload=None, method=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def main() -> int:
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu import checkpoint, telemetry
    from lightgbm_tpu.serving import CircuitBreaker, PredictionService
    from lightgbm_tpu.serving.http import serve
    from lightgbm_tpu.utils import faults

    tel_dir = sys.argv[1] if len(sys.argv) > 1 else None
    flight_dir = tel_dir or tempfile.mkdtemp(prefix="serve-smoke-flight-")
    os.environ["LGBM_TPU_FLIGHT_DIR"] = flight_dir
    if tel_dir:
        telemetry.start(tel_dir, label="serve_smoke")

    rng = np.random.RandomState(42)
    X = rng.rand(800, 12)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)

    with tempfile.TemporaryDirectory() as td:
        model_path = f"{td}/model.txt"
        checkpoint.save_checkpoint(bst, model_path)  # text + .ckpt sidecar

        # short breaker cooldown so the flap scenario recovers in-smoke
        svc = PredictionService(max_batch_rows=1024, batch_window_s=0.001,
                                breaker=CircuitBreaker(cooldown_s=0.4))
        server, _ = serve(svc, port=0)
        port = server.port
        failures = []

        def check(name, ok, detail=""):
            print(f"  [{'ok' if ok else 'FAIL'}] {name} {detail}")
            if not ok:
                failures.append(name)

        # checksum-verified load over HTTP (path + sidecar)
        status, info = _call(port, "/models",
                             {"name": "m", "path": model_path})
        check("verified load", status == 200 and info["verified"]
              and info["version"] == 1, str(info))

        status, ready = _call(port, "/readyz")
        check("readyz", status == 200 and ready["ready"])

        # concurrent bit-exact predicts
        queries = [rng.rand(int(n), 12) for n in rng.randint(1, 128, 16)]
        expected = [bst.predict(q).astype(np.float32) for q in queries]
        results = [None] * len(queries)

        def fire(i):
            s, body = _call(port, "/predict",
                            {"model": "m", "rows": queries[i].tolist()})
            if s == 200:
                results[i] = np.asarray(body["predictions"], np.float32)

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        exact = all(r is not None and np.array_equal(r, e)
                    for r, e in zip(results, expected))
        check("concurrent predicts bit-exact", exact)

        # corrupt upload REJECTED while v1 keeps serving
        faults.install("model_corrupt_upload")
        status, body = _call(port, "/models",
                             {"name": "m", "path": model_path})
        faults.clear()
        check("corrupt upload rejected",
              status == 400 and body.get("error") == "model_load_error")
        status, body = _call(port, "/predict",
                             {"model": "m", "rows": queries[0].tolist()})
        check("prior version still serving", status == 200
              and body["version"] == 1
              and np.array_equal(np.asarray(body["predictions"], np.float32),
                                 expected[0]))

        # deadline-expired request reports 504 without wedging the service
        faults.install("slow_predict@1:0.3")
        status, body = _call(port, "/predict",
                             {"model": "m", "rows": queries[0].tolist(),
                              "timeout_ms": 40})
        faults.clear()
        check("deadline exceeded is 504",
              status == 504 and body.get("error") == "deadline_exceeded",
              f"got {status}")

        # typed 400 on a malformed payload, naming the problem
        status, body = _call(port, "/predict",
                             {"model": "m", "rows": [[0.0] * 5]})
        check("typed 400 names feature count", status == 400
              and "5 features" in body.get("detail", ""))

        # binary wire path: bit-exact vs JSON, typed errors, traceparent
        from lightgbm_tpu.serving import wire

        def wire_call(body, traceparent=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body,
                headers={"Content-Type": wire.CONTENT_TYPE})
            if traceparent:
                req.add_header("traceparent", traceparent)
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return resp.status, resp.read(), dict(
                        (k.lower(), v) for k, v in resp.headers.items())
            except urllib.error.HTTPError as exc:
                return exc.code, exc.read(), dict(
                    (k.lower(), v) for k, v in exc.headers.items())

        qw = np.ascontiguousarray(queries[0], dtype=np.float32)
        status, body, headers = wire_call(wire.encode_request("m", qw))
        wire_ok = status == 200 \
            and headers["content-type"] == wire.CONTENT_TYPE
        if wire_ok:
            preds, _, _ = wire.decode_response(body)
            wire_ok = np.array_equal(preds, expected[0])
        check("binary wire bit-exact vs JSON", wire_ok)

        frame = wire.encode_request("m", qw)
        status, body, headers = wire_call(b"XXXX" + frame[4:])
        check("corrupt wire frame is a typed 400",
              status == 400
              and headers["content-type"].startswith("application/json")
              and json.loads(body).get("error") == "invalid_request",
              f"got {status}")

        trace = "00-" + "5e" * 16 + "-" + "6f" * 8 + "-01"
        status, _, headers = wire_call(
            wire.encode_request("m", qw, traceparent=trace))
        check("wire traceparent propagated", status == 200
              and headers.get("traceparent", "").split("-")[1] == "5e" * 16,
              headers.get("traceparent", "<none>"))

        # breaker flap under injected dispatch failures: requests keep
        # answering bit-exact from the host path while the breaker opens,
        # and the flight recorder auto-dumps the postmortem
        faults.install("predict_fail@1:10")
        flap_exact = True
        for _ in range(6):
            status, body = _call(port, "/predict",
                                 {"model": "m", "rows": queries[0].tolist()})
            flap_exact = flap_exact and status == 200 and np.array_equal(
                np.asarray(body["predictions"], np.float32), expected[0])
            if svc.breaker.state == "open":
                break
        faults.clear()
        check("breaker opened under predict_fail",
              svc.breaker.state == "open", svc.breaker.state)
        check("bit-exact 200s through the flap (host fallback)", flap_exact)

        dump_path = os.path.join(flight_dir, "flight-breaker_open.json")
        check("flight dump written on breaker open",
              os.path.isfile(dump_path), dump_path)
        dump = {}
        if os.path.isfile(dump_path):
            with open(dump_path, "r", encoding="utf-8") as fh:
                dump = json.load(fh)
        opens = [e for e in dump.get("events", [])
                 if e.get("kind") == "breaker_transition"
                 and e.get("new") == "open"]
        check("dump contains the OPEN transition", bool(opens))
        check("dump holds the events preceding the transition",
              bool(opens) and any(e["seq"] < opens[0]["seq"]
                                  for e in dump.get("events", [])))

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        fv = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "flightview.py"),
             dump_path, "--trace",
             # NOT flight-*.json: teldiff validates that glob as dumps
             os.path.join(flight_dir, "flightview-trace.json")],
            capture_output=True, text=True)
        check("flightview renders the dump", fv.returncode == 0,
              (fv.stderr or fv.stdout)[-200:])
        td = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "teldiff.py"),
             "--self-check", dump_path], capture_output=True, text=True)
        check("teldiff --self-check accepts the dump format",
              td.returncode == 0, (td.stderr or td.stdout)[-200:])

        # recovery: cooldown elapses, probe dispatches close the breaker
        time.sleep(0.5)
        for _ in range(5):
            _call(port, "/predict",
                  {"model": "m", "rows": queries[0].tolist()})
            if svc.breaker.state == "closed":
                break
        check("breaker recovered to closed", svc.breaker.state == "closed",
              svc.breaker.state)
        status, stz = _call(port, "/statz")
        check("statz surfaces the transition history", status == 200
              and any(t.get("new") == "open" for t in
                      stz["breaker"].get("last_transitions", [])))
        check("statz carries request stage quantiles", status == 200
              and stz.get("stages", {}).get("queue_wait", {})
                    .get("count", 0) > 0
              and "device" in stz.get("stages", {}), str(stz.get("stages"))[:200])

        # /healthz stays green through all of the above
        status, health = _call(port, "/healthz")
        check("healthz green", status == 200
              and health["status"] == "ok"
              and health["rejected_uploads"] == 1
              and health["queue"]["queue_rows"] == 0, str(health)[:200])

        server.shutdown()
        svc.close()

        # AOT cold start: a warm writer exports compiled executables; a
        # cold replica loading the same file must come up in a small
        # fraction of the compile-on-first-request time
        import jax

        warm = PredictionService(max_batch_rows=1024, batch_window_s=0.0)
        warm.load_model("m", path=model_path)
        warm.export_aot("m")
        warm.close()
        probe = np.ascontiguousarray(X[:256], dtype=np.float32)

        def cold_start_s(drop_aot):
            if drop_aot:
                os.remove(model_path + checkpoint.AOT_SUFFIX)
            jax.clear_caches()
            svc2 = PredictionService(max_batch_rows=1024,
                                     batch_window_s=0.0)
            t0 = time.perf_counter()
            info = svc2.load_model("cold", path=model_path)
            out = svc2.predict("cold", probe, raw_score=True)
            dt = time.perf_counter() - t0
            svc2.close()
            return dt, info["aot_buckets"], out

        t_aot, buckets, out_aot = cold_start_s(drop_aot=False)
        t_compile, no_buckets, out_cold = cold_start_s(drop_aot=True)
        check("AOT sidecar installed on cold load", buckets > 0
              and no_buckets == 0, f"{buckets}/{no_buckets}")
        check("AOT and compiled cold starts bit-identical",
              np.array_equal(out_aot, out_cold))
        check("AOT cold start <= 10% of compile cold start",
              t_aot <= 0.10 * t_compile,
              f"aot {t_aot * 1e3:.0f}ms vs compile {t_compile * 1e3:.0f}ms")

    if tel_dir:
        telemetry.stop()
    if failures:
        print(f"serve_smoke: FAILED ({', '.join(failures)})")
        return 1
    print("serve_smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
