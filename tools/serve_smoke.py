"""CI serving smoke: boot the hardened prediction server, drive it over
HTTP with concurrent clients — including a corrupt upload and a
deadline-expired request — and assert the service stays healthy and
bit-exact throughout.

    python tools/serve_smoke.py [telemetry_dir]

Exits nonzero on any violated invariant. When a telemetry dir is given the
run records a full event stream there (validate it afterwards with
`python tools/teldiff.py --self-check <dir>`).
"""
import json
import os
import sys
import tempfile
import threading
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _call(port, path, payload=None, method=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def main() -> int:
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu import checkpoint, telemetry
    from lightgbm_tpu.serving import PredictionService
    from lightgbm_tpu.serving.http import serve
    from lightgbm_tpu.utils import faults

    tel_dir = sys.argv[1] if len(sys.argv) > 1 else None
    if tel_dir:
        telemetry.start(tel_dir, label="serve_smoke")

    rng = np.random.RandomState(42)
    X = rng.rand(800, 12)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)

    with tempfile.TemporaryDirectory() as td:
        model_path = f"{td}/model.txt"
        checkpoint.save_checkpoint(bst, model_path)  # text + .ckpt sidecar

        svc = PredictionService(max_batch_rows=1024, batch_window_s=0.001)
        server, _ = serve(svc, port=0)
        port = server.port
        failures = []

        def check(name, ok, detail=""):
            print(f"  [{'ok' if ok else 'FAIL'}] {name} {detail}")
            if not ok:
                failures.append(name)

        # checksum-verified load over HTTP (path + sidecar)
        status, info = _call(port, "/models",
                             {"name": "m", "path": model_path})
        check("verified load", status == 200 and info["verified"]
              and info["version"] == 1, str(info))

        status, ready = _call(port, "/readyz")
        check("readyz", status == 200 and ready["ready"])

        # concurrent bit-exact predicts
        queries = [rng.rand(int(n), 12) for n in rng.randint(1, 128, 16)]
        expected = [bst.predict(q).astype(np.float32) for q in queries]
        results = [None] * len(queries)

        def fire(i):
            s, body = _call(port, "/predict",
                            {"model": "m", "rows": queries[i].tolist()})
            if s == 200:
                results[i] = np.asarray(body["predictions"], np.float32)

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        exact = all(r is not None and np.array_equal(r, e)
                    for r, e in zip(results, expected))
        check("concurrent predicts bit-exact", exact)

        # corrupt upload REJECTED while v1 keeps serving
        faults.install("model_corrupt_upload")
        status, body = _call(port, "/models",
                             {"name": "m", "path": model_path})
        faults.clear()
        check("corrupt upload rejected",
              status == 400 and body.get("error") == "model_load_error")
        status, body = _call(port, "/predict",
                             {"model": "m", "rows": queries[0].tolist()})
        check("prior version still serving", status == 200
              and body["version"] == 1
              and np.array_equal(np.asarray(body["predictions"], np.float32),
                                 expected[0]))

        # deadline-expired request reports 504 without wedging the service
        faults.install("slow_predict@1:0.3")
        status, body = _call(port, "/predict",
                             {"model": "m", "rows": queries[0].tolist(),
                              "timeout_ms": 40})
        faults.clear()
        check("deadline exceeded is 504",
              status == 504 and body.get("error") == "deadline_exceeded",
              f"got {status}")

        # typed 400 on a malformed payload, naming the problem
        status, body = _call(port, "/predict",
                             {"model": "m", "rows": [[0.0] * 5]})
        check("typed 400 names feature count", status == 400
              and "5 features" in body.get("detail", ""))

        # /healthz stays green through all of the above
        status, health = _call(port, "/healthz")
        check("healthz green", status == 200
              and health["status"] == "ok"
              and health["rejected_uploads"] == 1
              and health["queue"]["queue_rows"] == 0, str(health)[:200])

        server.shutdown()
        svc.close()

    if tel_dir:
        telemetry.stop()
    if failures:
        print(f"serve_smoke: FAILED ({', '.join(failures)})")
        return 1
    print("serve_smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
