"""CI streaming smoke: out-of-core training + continuous-refit flywheel.

    python tools/stream_smoke.py [telemetry_dir]

Drives the full docs/STREAMING.md story end to end and exits nonzero on
any violated invariant:

  1. chunked-iterator ingest through RowBlockStore (no raw matrix ever
     materialized in one piece);
  2. out-of-core training under an HBM budget 4x smaller than the bin
     plane, asserted BIT-IDENTICAL to the resident train;
  3. a mid-refit injected kill, resumed bit-identically from the
     generation checkpoint while fresh pushes keep landing;
  4. a refit -> hot-swap loop against a live PredictionService under
     concurrent predict load, with zero failed predicts;
  5. a planted drift_shift fault tripping the PSI alarm (flight dump on
     disk), a sketch-driven bin-mapper refresh that measurably restores
     bin resolution while the published model stays byte-identical, and
     a poisoned generation rejected by the holdout quality gate before
     a clean retry publishes;
  6. a REAL multi-process gang (2 jax.distributed workers over gloo)
     running the gang-sharded streamed path: sketch-merged bin fit +
     budgeted tree_learner=data train asserted BIT-identical to a
     world=1 run, then a planted kill mid-generation and a surviving
     single rank resuming the partial snapshot to the same bytes.
     Set LGBM_TPU_SMOKE_NO_POD=1 to skip (e.g. sandboxes without
     loopback sockets).

When a telemetry dir is given the run records a full event stream there
(validate with `python tools/teldiff.py --self-check <dir>`).
"""
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Phase-6 worker, written to the workdir at run time. Modes:
#   solo   -- world=1 reference: generation 0 + generation 1, no faults;
#   gang   -- one rank of the 2-process gloo gang: generation 0, then a
#             planted kill@3 that fells every rank at the same iteration
#             of generation 1, leaving the gen-1 snapshot at iteration 2;
#   resume -- the surviving rank continuing ALONE (world=1): its fresh
#             checkpoint dir holds ONLY the gang's partial gen-1
#             snapshot, so generation 0 retrains fresh and generation 1
#             resumes mid-generation from the copied checkpoint.
_POD_WORKER_SRC = '''\
import os
import sys


def main() -> int:
    mode, ckpt_dir, out_path = sys.argv[1], sys.argv[2], sys.argv[3]
    import numpy as np

    from lightgbm_tpu.parallel.dist import init_distributed
    init_distributed()  # picks up the JAX_* triple; gloo on the CPU gang
    import jax

    from lightgbm_tpu.streaming import ContinuousTrainer, \\
        ShardedRowBlockStore
    from lightgbm_tpu.utils import faults
    from lightgbm_tpu.utils.faults import InjectedFault
    from lightgbm_tpu.utils.timer import global_timer

    world = jax.process_count()
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.1, "verbosity": -1, "min_data_in_leaf": 5,
              "tree_learner": "data", "use_quantized_grad": True}
    rng = np.random.RandomState(17)
    n, f = 2048, 8
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.3 > 0
         ).astype(np.float64)

    # gang contract: every rank receives the full push stream and owns
    # the blocks that land on its shard; the bin fit merges per-rank
    # sketches over a real cross-process allgather
    store = ShardedRowBlockStore(params=params, bin_sample_rows=1024)
    for lo in range(0, 1024, 256):
        store.push_rows(X[lo:lo + 256], label=y[lo:lo + 256])
    assert store.num_shards == world, (store.num_shards, world)
    if world > 1:
        assert global_timer.counters.get("stream_sketch_merges", 0) >= 1, \\
            "gang fit never merged sketches across ranks"

    # starved budget: 2 resident blocks of 8 -> the streamed learner
    groups = len(store._group_lists)
    os.environ["LGBM_TPU_STREAM_BLOCK_ROWS"] = "256"
    os.environ["LGBM_TPU_HBM_BUDGET"] = str(2 * groups * 256)

    tr = ContinuousTrainer(params, store, num_boost_round=5,
                           checkpoint_dir=ckpt_dir)
    b0 = tr.refit()
    with open(out_path + ".gen0", "w") as fh:
        fh.write(b0.model_to_string())
    for lo in range(1024, 2048, 256):
        store.push_rows(X[lo:lo + 256], label=y[lo:lo + 256])
    if mode == "gang":
        faults.install("kill@3")
        try:
            tr.step()
            raise AssertionError("planted kill@3 did not fire")
        except InjectedFault:
            return 0  # generation 1 died; its snapshot holds iteration 2
        finally:
            faults.clear()
    b1 = tr.step()
    with open(out_path, "w") as fh:
        fh.write(b1.model_to_string())
    return 0


if __name__ == "__main__":
    sys.exit(main())
'''


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _read(path: str) -> str:
    with open(path) as fh:
        return fh.read()


def _pod_phase() -> None:
    """Phase 6: spawn a REAL 2-process jax.distributed gang (gloo on CPU)
    through the phase-6 worker, prove the gang-sharded streamed train is
    bit-identical to a world=1 run, then resume the gang's killed
    generation on a single surviving rank and prove the SAME bytes."""
    import glob
    import shutil

    from lightgbm_tpu.parallel.elastic import worker_env

    workdir = tempfile.mkdtemp(prefix="stream-smoke-pod-")
    worker = os.path.join(workdir, "pod_worker.py")
    with open(worker, "w") as fh:
        fh.write(_POD_WORKER_SRC)

    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = _REPO
    base_env["JAX_PLATFORMS"] = "cpu"
    # bit-identity across world sizes needs a fixed wave schedule
    base_env["LGBM_TPU_ADAPTIVE_WAVE"] = "0"
    base_env.pop("XLA_FLAGS", None)
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID", "LGBM_TPU_HBM_BUDGET",
              "LGBM_TPU_STREAM_BLOCK_ROWS"):
        base_env.pop(k, None)

    def run_solo(mode: str, ckpt_dir: str, out: str) -> None:
        r = subprocess.run(
            [sys.executable, worker, mode, ckpt_dir, out], env=base_env,
            cwd=_REPO, capture_output=True, text=True, timeout=480)
        assert r.returncode == 0, (
            f"pod {mode} worker rc={r.returncode}\n"
            + (r.stdout + r.stderr)[-2000:])

    solo_out = os.path.join(workdir, "solo.txt")
    run_solo("solo", os.path.join(workdir, "ckpt_solo"), solo_out)

    # the gang: 2 jax.distributed processes, 1 virtual CPU device each;
    # per-rank checkpoint dirs (identical bytes, but no shared tmp races)
    port = _free_port()
    t0 = time.monotonic()
    procs = []
    for rank in range(2):
        env = worker_env(base_env, port=port, world=2, rank=rank,
                         attempt=0, elastic=False, devices_per_proc=1)
        procs.append(subprocess.Popen(
            [sys.executable, worker, "gang",
             os.path.join(workdir, f"ckpt_gang_r{rank}"),
             os.path.join(workdir, f"gang_r{rank}.txt")],
            env=env, cwd=_REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    deadline = time.monotonic() + 480
    for p in procs:
        rc = p.wait(timeout=max(1.0, deadline - time.monotonic()))
        out = p.stdout.read()
        assert rc == 0, f"pod gang worker rc={rc}\n{out[-2000:]}"
    gang_s = time.monotonic() - t0

    solo_gen0 = _read(solo_out + ".gen0")
    gang_gen0 = _read(os.path.join(workdir, "gang_r0.txt.gen0"))
    assert gang_gen0 == _read(os.path.join(workdir, "gang_r1.txt.gen0")), \
        "gang ranks published different generation-0 models"
    assert gang_gen0 == solo_gen0, \
        "2-process sharded train diverged from the world=1 run"

    # surviving-rank resume: a fresh world=1 worker whose checkpoint dir
    # holds ONLY the gang's partial generation-1 snapshot
    partial = glob.glob(
        os.path.join(workdir, "ckpt_gang_r0", "refit_gen0001.txt*"))
    assert partial, "gang kill left no partial generation-1 snapshot"
    resume_ckpt = os.path.join(workdir, "ckpt_resume")
    os.makedirs(resume_ckpt)
    for p in partial:
        shutil.copy(p, resume_ckpt)
    resume_out = os.path.join(workdir, "resume.txt")
    run_solo("resume", resume_ckpt, resume_out)
    assert _read(resume_out + ".gen0") == solo_gen0
    assert _read(resume_out) == _read(solo_out), \
        "surviving-rank resume diverged from the undisturbed run"
    shutil.rmtree(workdir, ignore_errors=True)
    print(f"# pod: 2-process gloo gang bit-identical to world=1 and a "
          f"surviving rank resumed the killed generation to the same "
          f"bytes ({gang_s:.1f}s gang wall)")


def main() -> int:
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.engine import train
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serving import PredictionService
    from lightgbm_tpu.streaming import ContinuousTrainer, RowBlockStore
    from lightgbm_tpu.utils import faults
    from lightgbm_tpu.utils.faults import InjectedFault
    from lightgbm_tpu.utils.timer import global_timer

    tel_dir = sys.argv[1] if len(sys.argv) > 1 else None
    if tel_dir:
        telemetry.start(tel_dir, label="stream_smoke")

    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
              "verbosity": -1, "min_data_in_leaf": 5}
    rng = np.random.RandomState(11)
    n, f = 4096, 10
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.3 > 0
         ).astype(np.float64)

    try:
        # -- 1. chunked-iterator ingest ---------------------------------
        def block_source(lo_hi_step=512):
            for lo in range(0, n, lo_hi_step):
                hi = min(n, lo + lo_hi_step)
                yield X[lo:hi], y[lo:hi]

        store = RowBlockStore(params=params)
        store.push_from_iterator(block_source())
        assert store.total_rows == n, store.total_rows
        ingest_ds = store.to_basic_dataset(params=params)
        print(f"# ingest: {n} rows in {n // 512} iterator blocks, "
              f"{int(global_timer.counters.get('stream_ingest_bytes', 0))} "
              "raw bytes binned")

        # -- 2. out-of-core train, bit-identical ------------------------
        resident = train(dict(params), lgb.Dataset(X, label=y),
                         num_boost_round=6)
        core = CoreDataset.from_matrix(X, label=y, config=Config(dict(params)))
        plane_bytes = core.bins.size * core.bins.dtype.itemsize
        block_bytes = core.bins.shape[0] * 256
        budget = 2 * block_bytes
        assert plane_bytes >= 4 * budget, (plane_bytes, budget)
        os.environ["LGBM_TPU_STREAM_BLOCK_ROWS"] = "256"
        os.environ["LGBM_TPU_HBM_BUDGET"] = str(budget)
        try:
            streamed = train(dict(params), ingest_ds, num_boost_round=6)
        finally:
            os.environ.pop("LGBM_TPU_HBM_BUDGET", None)
            os.environ.pop("LGBM_TPU_STREAM_BLOCK_ROWS", None)
        assert streamed.model_to_string() == resident.model_to_string(), \
            "streamed model diverged from resident"
        c = global_timer.counters
        frac = c["stream_resident_blocks"] / c["stream_blocks_total"]
        print(f"# out-of-core: bit-identical under budget={budget}B "
              f"(resident fraction {frac:.2f}, "
              f"{int(c.get('stream_h2d_blocks', 0))} block uploads)")

        # -- 3. kill mid-refit, resume bit-identically -------------------
        with tempfile.TemporaryDirectory() as ckpt_dir:
            def filled():
                s = RowBlockStore(params=params)
                for lo in range(0, 2048, 512):
                    s.push_rows(X[lo:lo + 512], label=y[lo:lo + 512])
                return s

            straight = ContinuousTrainer(
                params, filled(), num_boost_round=5,
                checkpoint_dir=os.path.join(ckpt_dir, "a")).refit()
            crashy_store = filled()
            crashy = ContinuousTrainer(
                params, crashy_store, num_boost_round=5,
                checkpoint_dir=os.path.join(ckpt_dir, "b"))
            faults.install("kill@3")
            try:
                crashy.step()
                raise AssertionError("injected kill did not fire")
            except InjectedFault:
                pass
            faults.clear()
            # fresh rows land while the refit is down; the watermark must
            # keep the retried generation pinned to the pre-crash range
            crashy_store.push_rows(X[2048:2560], label=y[2048:2560])
            resumed = crashy.step()
            assert resumed.model_to_string() == straight.model_to_string(), \
                "resumed refit diverged from uninterrupted refit"
            print("# crash-resume: generation checkpoint replayed "
                  "bit-identically with pushes landing mid-outage")

        # -- 4. refit -> hot-swap under concurrent predicts --------------
        live_store = RowBlockStore(params=params)
        live_store.push_rows(X[:1024], label=y[:1024])
        svc = PredictionService(max_batch_rows=512, batch_window_s=0.0005)
        flywheel = ContinuousTrainer(params, live_store, num_boost_round=3,
                                     service=svc, model_name="live")
        failures = []
        try:
            flywheel.refit()
            done = threading.Event()

            def hammer():
                while not done.is_set():
                    try:
                        out = svc.predict("live", X[:16], raw_score=True)
                        assert out.shape[0] == 16
                    except Exception as e:  # noqa: BLE001 - the invariant
                        failures.append(repr(e))

            threads = [threading.Thread(target=hammer) for _ in range(3)]
            for t in threads:
                t.start()
            for lo in (1024, 2048):
                live_store.push_rows(X[lo:lo + 1024], label=y[lo:lo + 1024])
                flywheel.step()
            done.set()
            for t in threads:
                t.join()
        finally:
            svc.close()
        assert failures == [], failures[:3]
        assert flywheel.generation == 3, flywheel.generation
        assert svc.registry.get("live").version == 3
        print("# flywheel: 3 generations hot-swapped, 0 failed predicts")

        # -- 5. drift alarm -> bin refresh -> quality-gated publish ------
        d_saved = {k: os.environ.get(k) for k in
                   ("LGBM_TPU_DRIFT", "LGBM_TPU_DRIFT_CHECK_ROWS",
                    "LGBM_TPU_FLIGHT_DIR")}
        # flight dumps land next to the event stream when a telemetry dir
        # is given, so the CI artifact ships the drift postmortems too
        flight_dir = tel_dir or tempfile.mkdtemp(prefix="stream-smoke-flight-")
        os.environ["LGBM_TPU_DRIFT"] = "1"
        os.environ["LGBM_TPU_DRIFT_CHECK_ROWS"] = "512"
        os.environ["LGBM_TPU_FLIGHT_DIR"] = flight_dir
        faults.install("drift_shift@1024:0")
        try:
            dstore = RowBlockStore(params=params, bin_sample_rows=1024)
            dtr = ContinuousTrainer(params, dstore, num_boost_round=3,
                                    holdout_rows=512)
            dstore.push_rows(X[:1024], label=y[:1024])
            old_text = dtr.step().model_to_string()
            for lo in range(1024, 3072, 512):
                dstore.push_rows(X[lo:lo + 512], label=y[lo:lo + 512])
            mon = dstore._drift
            assert mon is not None and mon.alarmed, "drift alarm missing"
            assert mon.alarm_feature == 0, mon.alarm_feature
            assert os.path.exists(
                os.path.join(flight_dir, "flight-drift_alarm.json")), \
                "drift alarm fired without a flight dump"
            shifted = X[1024:2048, 0] * 3.0 + 10.0  # the fault's transform
            mapper0 = dstore._layout.mappers[0]
            bins_before = len(np.unique(mapper0.values_to_bins(shifted)))
            assert dstore.maybe_refresh_bins() is True, "refresh was a no-op"
            assert dstore.layout_generation == 1
            mapper0 = dstore._layout.mappers[0]
            bins_after = len(np.unique(mapper0.values_to_bins(shifted)))
            assert bins_after > bins_before, (bins_before, bins_after)
            assert dtr.booster.model_to_string() == old_text, \
                "bin refresh mutated the published model"
            faults.clear()
            # gate: a poisoned candidate never publishes, serving untouched
            faults.install("bad_generation@1")
            assert dtr.step() is None, "poisoned generation passed the gate"
            assert dtr.generation == 1, dtr.generation
            assert dtr.booster.model_to_string() == old_text
            faults.clear()
            assert dtr.step() is not None, "clean retry failed to publish"
            assert dtr.generation == 2, dtr.generation
            print(f"# drift: alarm on feature 0, refresh restored "
                  f"{bins_before}->{bins_after} distinct bins, 1 poisoned "
                  "generation rejected, published model byte-identical")
        finally:
            faults.clear()
            for k, v in d_saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

        # -- 6. multi-process gang: sharded fit + surviving-rank resume --
        if os.environ.get("LGBM_TPU_SMOKE_NO_POD", "") not in ("1", "true"):
            _pod_phase()
        else:
            print("# pod: skipped (LGBM_TPU_SMOKE_NO_POD)")
    finally:
        if tel_dir:
            telemetry.stop()
    print("# stream smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
