"""CI streaming smoke: out-of-core training + continuous-refit flywheel.

    python tools/stream_smoke.py [telemetry_dir]

Drives the full docs/STREAMING.md story end to end and exits nonzero on
any violated invariant:

  1. chunked-iterator ingest through RowBlockStore (no raw matrix ever
     materialized in one piece);
  2. out-of-core training under an HBM budget 4x smaller than the bin
     plane, asserted BIT-IDENTICAL to the resident train;
  3. a mid-refit injected kill, resumed bit-identically from the
     generation checkpoint while fresh pushes keep landing;
  4. a refit -> hot-swap loop against a live PredictionService under
     concurrent predict load, with zero failed predicts;
  5. a planted drift_shift fault tripping the PSI alarm (flight dump on
     disk), a sketch-driven bin-mapper refresh that measurably restores
     bin resolution while the published model stays byte-identical, and
     a poisoned generation rejected by the holdout quality gate before
     a clean retry publishes.

When a telemetry dir is given the run records a full event stream there
(validate with `python tools/teldiff.py --self-check <dir>`).
"""
import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.engine import train
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serving import PredictionService
    from lightgbm_tpu.streaming import ContinuousTrainer, RowBlockStore
    from lightgbm_tpu.utils import faults
    from lightgbm_tpu.utils.faults import InjectedFault
    from lightgbm_tpu.utils.timer import global_timer

    tel_dir = sys.argv[1] if len(sys.argv) > 1 else None
    if tel_dir:
        telemetry.start(tel_dir, label="stream_smoke")

    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
              "verbosity": -1, "min_data_in_leaf": 5}
    rng = np.random.RandomState(11)
    n, f = 4096, 10
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.3 > 0
         ).astype(np.float64)

    try:
        # -- 1. chunked-iterator ingest ---------------------------------
        def block_source(lo_hi_step=512):
            for lo in range(0, n, lo_hi_step):
                hi = min(n, lo + lo_hi_step)
                yield X[lo:hi], y[lo:hi]

        store = RowBlockStore(params=params)
        store.push_from_iterator(block_source())
        assert store.total_rows == n, store.total_rows
        ingest_ds = store.to_basic_dataset(params=params)
        print(f"# ingest: {n} rows in {n // 512} iterator blocks, "
              f"{int(global_timer.counters.get('stream_ingest_bytes', 0))} "
              "raw bytes binned")

        # -- 2. out-of-core train, bit-identical ------------------------
        resident = train(dict(params), lgb.Dataset(X, label=y),
                         num_boost_round=6)
        core = CoreDataset.from_matrix(X, label=y, config=Config(dict(params)))
        plane_bytes = core.bins.size * core.bins.dtype.itemsize
        block_bytes = core.bins.shape[0] * 256
        budget = 2 * block_bytes
        assert plane_bytes >= 4 * budget, (plane_bytes, budget)
        os.environ["LGBM_TPU_STREAM_BLOCK_ROWS"] = "256"
        os.environ["LGBM_TPU_HBM_BUDGET"] = str(budget)
        try:
            streamed = train(dict(params), ingest_ds, num_boost_round=6)
        finally:
            os.environ.pop("LGBM_TPU_HBM_BUDGET", None)
            os.environ.pop("LGBM_TPU_STREAM_BLOCK_ROWS", None)
        assert streamed.model_to_string() == resident.model_to_string(), \
            "streamed model diverged from resident"
        c = global_timer.counters
        frac = c["stream_resident_blocks"] / c["stream_blocks_total"]
        print(f"# out-of-core: bit-identical under budget={budget}B "
              f"(resident fraction {frac:.2f}, "
              f"{int(c.get('stream_h2d_blocks', 0))} block uploads)")

        # -- 3. kill mid-refit, resume bit-identically -------------------
        with tempfile.TemporaryDirectory() as ckpt_dir:
            def filled():
                s = RowBlockStore(params=params)
                for lo in range(0, 2048, 512):
                    s.push_rows(X[lo:lo + 512], label=y[lo:lo + 512])
                return s

            straight = ContinuousTrainer(
                params, filled(), num_boost_round=5,
                checkpoint_dir=os.path.join(ckpt_dir, "a")).refit()
            crashy_store = filled()
            crashy = ContinuousTrainer(
                params, crashy_store, num_boost_round=5,
                checkpoint_dir=os.path.join(ckpt_dir, "b"))
            faults.install("kill@3")
            try:
                crashy.step()
                raise AssertionError("injected kill did not fire")
            except InjectedFault:
                pass
            faults.clear()
            # fresh rows land while the refit is down; the watermark must
            # keep the retried generation pinned to the pre-crash range
            crashy_store.push_rows(X[2048:2560], label=y[2048:2560])
            resumed = crashy.step()
            assert resumed.model_to_string() == straight.model_to_string(), \
                "resumed refit diverged from uninterrupted refit"
            print("# crash-resume: generation checkpoint replayed "
                  "bit-identically with pushes landing mid-outage")

        # -- 4. refit -> hot-swap under concurrent predicts --------------
        live_store = RowBlockStore(params=params)
        live_store.push_rows(X[:1024], label=y[:1024])
        svc = PredictionService(max_batch_rows=512, batch_window_s=0.0005)
        flywheel = ContinuousTrainer(params, live_store, num_boost_round=3,
                                     service=svc, model_name="live")
        failures = []
        try:
            flywheel.refit()
            done = threading.Event()

            def hammer():
                while not done.is_set():
                    try:
                        out = svc.predict("live", X[:16], raw_score=True)
                        assert out.shape[0] == 16
                    except Exception as e:  # noqa: BLE001 - the invariant
                        failures.append(repr(e))

            threads = [threading.Thread(target=hammer) for _ in range(3)]
            for t in threads:
                t.start()
            for lo in (1024, 2048):
                live_store.push_rows(X[lo:lo + 1024], label=y[lo:lo + 1024])
                flywheel.step()
            done.set()
            for t in threads:
                t.join()
        finally:
            svc.close()
        assert failures == [], failures[:3]
        assert flywheel.generation == 3, flywheel.generation
        assert svc.registry.get("live").version == 3
        print("# flywheel: 3 generations hot-swapped, 0 failed predicts")

        # -- 5. drift alarm -> bin refresh -> quality-gated publish ------
        d_saved = {k: os.environ.get(k) for k in
                   ("LGBM_TPU_DRIFT", "LGBM_TPU_DRIFT_CHECK_ROWS",
                    "LGBM_TPU_FLIGHT_DIR")}
        # flight dumps land next to the event stream when a telemetry dir
        # is given, so the CI artifact ships the drift postmortems too
        flight_dir = tel_dir or tempfile.mkdtemp(prefix="stream-smoke-flight-")
        os.environ["LGBM_TPU_DRIFT"] = "1"
        os.environ["LGBM_TPU_DRIFT_CHECK_ROWS"] = "512"
        os.environ["LGBM_TPU_FLIGHT_DIR"] = flight_dir
        faults.install("drift_shift@1024:0")
        try:
            dstore = RowBlockStore(params=params, bin_sample_rows=1024)
            dtr = ContinuousTrainer(params, dstore, num_boost_round=3,
                                    holdout_rows=512)
            dstore.push_rows(X[:1024], label=y[:1024])
            old_text = dtr.step().model_to_string()
            for lo in range(1024, 3072, 512):
                dstore.push_rows(X[lo:lo + 512], label=y[lo:lo + 512])
            mon = dstore._drift
            assert mon is not None and mon.alarmed, "drift alarm missing"
            assert mon.alarm_feature == 0, mon.alarm_feature
            assert os.path.exists(
                os.path.join(flight_dir, "flight-drift_alarm.json")), \
                "drift alarm fired without a flight dump"
            shifted = X[1024:2048, 0] * 3.0 + 10.0  # the fault's transform
            mapper0 = dstore._layout.mappers[0]
            bins_before = len(np.unique(mapper0.values_to_bins(shifted)))
            assert dstore.maybe_refresh_bins() is True, "refresh was a no-op"
            assert dstore.layout_generation == 1
            mapper0 = dstore._layout.mappers[0]
            bins_after = len(np.unique(mapper0.values_to_bins(shifted)))
            assert bins_after > bins_before, (bins_before, bins_after)
            assert dtr.booster.model_to_string() == old_text, \
                "bin refresh mutated the published model"
            faults.clear()
            # gate: a poisoned candidate never publishes, serving untouched
            faults.install("bad_generation@1")
            assert dtr.step() is None, "poisoned generation passed the gate"
            assert dtr.generation == 1, dtr.generation
            assert dtr.booster.model_to_string() == old_text
            faults.clear()
            assert dtr.step() is not None, "clean retry failed to publish"
            assert dtr.generation == 2, dtr.generation
            print(f"# drift: alarm on feature 0, refresh restored "
                  f"{bins_before}->{bins_after} distinct bins, 1 poisoned "
                  "generation rejected, published model byte-identical")
        finally:
            faults.clear()
            for k, v in d_saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    finally:
        if tel_dir:
            telemetry.stop()
    print("# stream smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
