#!/usr/bin/env python3
"""Summarize or diff lightgbm_tpu telemetry runs (stdlib only).

A telemetry run directory (written by lightgbm_tpu/telemetry.py when
`telemetry_dir` / $LGBM_TPU_TELEMETRY is set) holds:

    events.jsonl   one JSON object per line; the final `session_end` record
                   carries the per-label timer totals, work counters, and
                   watcher summaries this tool reads
    trace.json     Chrome trace-event JSON (Perfetto / chrome://tracing)

Usage:

    python tools/teldiff.py summarize RUN_DIR
    python tools/teldiff.py diff BASE_DIR CAND_DIR [--threshold PCT]
    python tools/teldiff.py --self-check RUN_DIR

`diff` prints per-label time and counter deltas and exits nonzero when any
tracked figure regresses by more than --threshold percent (default 10) —
the machine check "bench before/after" needs. Gating is direction-aware
(COUNTER_DIRECTIONS): time and byte figures regress UPWARD, while counters
like committed splits or predict-cache hits regress DOWNWARD — a symmetric
threshold cannot tell an optimization from a regression. `summarize` also
prints per-label span-duration p50/p99 recovered from trace.json.
`--self-check` validates a
run's artifacts (parseable JSONL, required event types, monotonic trace
timestamps, matched B/E span pairs) and exits nonzero on any violation —
CI runs it on the smoke-train artifact. It also accepts the flight-
recorder dump format (lightgbm_tpu/tracing.py): pass a `flight-*.json`
file directly, or a run dir — any flight dumps sitting in the dir are
validated alongside the event stream.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

EVENTS_FILE = "events.jsonl"
TRACE_FILE = "trace.json"
# per-counter DIRECTION for --threshold gating: "lower" means a higher
# value is a regression (bytes moved, compiles, speculation waste);
# "higher" means a DROP is the regression (work the optimizer is supposed
# to keep, e.g. committed splits or predict-cache hits falling means the
# fast path stopped engaging). Counters not listed are reported but never
# gate the exit code.
COUNTER_DIRECTIONS: Dict[str, str] = {
    "jit_compiles": "lower",
    "kernel_compiles": "lower",
    "hbm_high_water_bytes": "lower",
    "device_hist_rows": "lower",
    "device_ici_bytes_per_wave": "lower",
    "device_carry_bytes_per_wave": "lower",
    "device_scan_bytes_per_wave": "lower",
    "device_hist_bytes_per_row": "lower",
    "wave_splits_speculated": "lower",
    "device_waves": "lower",
    "wave_splits_committed": "higher",
    "predict_pack_hits": "higher",
}


def _read_events(run_dir: str) -> List[Dict[str, Any]]:
    path = os.path.join(run_dir, EVENTS_FILE)
    if not os.path.isfile(path):
        sys.exit(f"teldiff: no {EVENTS_FILE} in {run_dir}")
    events = []
    with open(path, "r", encoding="utf-8") as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                sys.exit(f"teldiff: {path}:{ln}: invalid JSON ({e})")
    if not events:
        sys.exit(f"teldiff: {path} is empty")
    return events


def _session_end(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    for ev in reversed(events):
        if ev.get("ev") == "session_end":
            return ev
    sys.exit("teldiff: no session_end record — run did not close cleanly")


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _span_durations(run_dir: str) -> Dict[str, List[float]]:
    """Per-label span durations (ms) from trace.json's B/E pairs. Labels
    never self-nest (one tid per label — telemetry.build_chrome_trace), so
    a simple per-track open-stack recovers every duration."""
    path = os.path.join(run_dir, TRACE_FILE)
    if not os.path.isfile(path):
        return {}
    try:
        with open(path, "r", encoding="utf-8") as fh:
            trace = json.load(fh)
    except json.JSONDecodeError:
        return {}
    open_ts: Dict[Tuple[int, int], List[int]] = {}
    durations: Dict[str, List[float]] = {}
    for ev in trace.get("traceEvents", []):
        ph = ev.get("ph")
        if ph not in ("B", "E"):
            continue
        key = (ev.get("pid", 0), ev.get("tid", 0))
        if ph == "B":
            open_ts.setdefault(key, []).append(int(ev.get("ts", 0)))
        else:
            stack = open_ts.get(key)
            if stack:
                t0 = stack.pop()
                durations.setdefault(str(ev.get("name", "?")), []).append(
                    (int(ev.get("ts", 0)) - t0) / 1000.0)
    return durations


def _print_span_percentiles(run_dir: str) -> None:
    durations = _span_durations(run_dir)
    if not durations:
        return
    print("span durations (ms):")
    print(f"  {'label':<24} {'n':>6} {'p50':>10} {'p99':>10} {'max':>10}")
    for label in sorted(durations,
                        key=lambda k: -sum(durations[k])):
        vals = sorted(durations[label])
        print(f"  {label:<24} {len(vals):>6} "
              f"{_percentile(vals, 50):>10.3f} "
              f"{_percentile(vals, 99):>10.3f} {vals[-1]:>10.3f}")


def summarize(run_dir: str) -> int:
    events = _read_events(run_dir)
    end = _session_end(events)
    iters = [e for e in events if e.get("ev") == "iteration"]
    print(f"run: {run_dir}")
    print(f"label: {end.get('label')}  duration: {end.get('duration_s')}s  "
          f"events: {sum(end.get('events', {}).values())}  "
          f"iterations: {len(iters)}")
    if end.get("compile_count"):
        print(f"jit compiles: {end['compile_count']}")
    if end.get("hbm_high_water_bytes"):
        print("hbm high water: "
              f"{_fmt_bytes(end['hbm_high_water_bytes'])}")
    totals = end.get("timer_totals", {})
    counts = end.get("timer_counts", {})
    if totals:
        print("timer totals:")
        for label in sorted(totals, key=lambda k: (-totals[k], k)):
            print(f"  {label:<24} {totals[label]:>10.3f}s "
                  f"({counts.get(label, 0)} calls)")
    counters = end.get("counters", {})
    if counters:
        print("counters:")
        for label in sorted(counters):
            print(f"  {label:<32} {counters[label]}")
    if iters:
        walls = sorted(e.get("wall_s", 0.0) for e in iters)
        mid = walls[len(walls) // 2]
        print(f"per-iteration wall: median {mid:.4f}s  "
              f"min {walls[0]:.4f}s  max {walls[-1]:.4f}s")
    _print_span_percentiles(run_dir)
    return 0


def _pct(base: float, cand: float) -> Optional[float]:
    if base == 0:
        return None if cand == 0 else float("inf")
    return (cand - base) / abs(base) * 100.0


def diff(base_dir: str, cand_dir: str, threshold: float) -> int:
    base = _session_end(_read_events(base_dir))
    cand = _session_end(_read_events(cand_dir))
    regressions: List[str] = []

    def _section(name: str, b: Dict[str, Any], c: Dict[str, Any],
                 directions: Any, unit: str) -> None:
        # directions: "lower" applied to every key, or a per-key map —
        # a +15% in committed splits must not gate like +15% in bytes
        keys = sorted(set(b) | set(c))
        if not keys:
            return
        print(f"{name}:")
        for k in keys:
            bv, cv = float(b.get(k, 0)), float(c.get(k, 0))
            p = _pct(bv, cv)
            ptxt = "   (new)" if p == float("inf") else (
                "" if p is None else f" {p:+8.1f}%")
            direction = directions if isinstance(directions, str) \
                else directions.get(k)
            dtxt = f"  [{direction}-is-better]" if direction else ""
            print(f"  {k:<32} {bv:>12g} -> {cv:>12g}{unit}{ptxt}{dtxt}")
            if direction is None or p is None:
                continue
            bad_pct = p if direction == "lower" else -p
            if bad_pct > threshold:
                regressions.append(
                    f"{k}: {bv:g} -> {cv:g} ({p:+.1f}%, "
                    f"{direction}-is-better)")

    _section("timer totals (s)", base.get("timer_totals", {}),
             cand.get("timer_totals", {}), "lower", "s")
    _section("counters", base.get("counters", {}),
             cand.get("counters", {}), COUNTER_DIRECTIONS, "")
    for scalar in ("compile_count", "hbm_high_water_bytes", "duration_s"):
        bv, cv = float(base.get(scalar, 0)), float(cand.get(scalar, 0))
        if bv or cv:
            p = _pct(bv, cv)
            ptxt = "" if p is None else (
                " (new)" if p == float("inf") else f" ({p:+.1f}%)")
            print(f"{scalar}: {bv:g} -> {cv:g}{ptxt}")
            if scalar != "duration_s" and p is not None and p > threshold:
                regressions.append(f"{scalar}: {bv:g} -> {cv:g}")
    if regressions:
        print(f"\nREGRESSIONS past {threshold:g}% threshold:",
              file=sys.stderr)
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
        return 1
    print(f"\nno regressions past {threshold:g}% threshold")
    return 0


FLIGHT_FORMAT = "lgbm-flight"


def check_flight_dump(path: str) -> List[str]:
    """Validate one flight-recorder dump: format/version header, events
    as a list of seq-ordered records with numeric timestamps, counter
    map, drop accounting. Returns problems ([] = valid)."""
    problems: List[str] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            dump = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable flight dump ({e})"]
    if not isinstance(dump, dict) or dump.get("format") != FLIGHT_FORMAT:
        return [f"{path}: not a {FLIGHT_FORMAT} dump"]
    if not isinstance(dump.get("version"), int):
        problems.append(f"{path}: missing integer version")
    if not dump.get("reason"):
        problems.append(f"{path}: missing reason")
    events = dump.get("events")
    if not isinstance(events, list):
        problems.append(f"{path}: events is not a list")
        events = []
    last_seq = -1
    for ev in events:
        if not isinstance(ev, dict) or not isinstance(ev.get("seq"), int) \
                or not isinstance(ev.get("t"), (int, float)) \
                or not ev.get("kind"):
            problems.append(f"{path}: malformed ring record: {ev}")
            break
        if ev["seq"] <= last_seq:
            problems.append(
                f"{path}: ring seq not strictly increasing at {ev['seq']}")
            break
        last_seq = ev["seq"]
    if not isinstance(dump.get("counters"), dict):
        problems.append(f"{path}: missing counters map")
    dropped = dump.get("dropped")
    total = dump.get("total_records")
    if not isinstance(dropped, int) or not isinstance(total, int) \
            or dropped < 0 or dropped > total:
        problems.append(f"{path}: inconsistent drop accounting "
                        f"(dropped={dropped} total={total})")
    return problems


def self_check(run_dir: str) -> int:
    """Artifact validity: parseable JSONL with the required event types,
    trace.json with monotonic timestamps and matched B/E span pairs.
    A `flight-*.json` path validates as a flight dump instead; a run dir
    containing flight dumps validates those too."""
    if os.path.isfile(run_dir):
        problems = check_flight_dump(run_dir)
        if problems:
            for p in problems:
                print(f"self-check FAIL: {p}", file=sys.stderr)
            return 1
        print(f"self-check OK: {run_dir} (flight dump)")
        return 0
    problems: List[str] = []
    events = _read_events(run_dir)  # exits on parse failure
    types = {e.get("ev") for e in events}
    for required in ("session_start", "session_end"):
        if required not in types:
            problems.append(f"events.jsonl: missing {required} event")
    for e in events:
        if not isinstance(e.get("t"), (int, float)):
            problems.append(f"events.jsonl: event without numeric t: {e}")
            break
    trace_path = os.path.join(run_dir, TRACE_FILE)
    if not os.path.isfile(trace_path):
        problems.append(f"missing {TRACE_FILE}")
    else:
        try:
            with open(trace_path, "r", encoding="utf-8") as fh:
                trace = json.load(fh)
        except json.JSONDecodeError as e:
            problems.append(f"{TRACE_FILE}: invalid JSON ({e})")
            trace = None
        if trace is not None:
            tev = trace.get("traceEvents")
            if not isinstance(tev, list):
                problems.append(f"{TRACE_FILE}: no traceEvents list")
                tev = []
            last_ts = -1
            depth: Dict[Tuple[int, int], int] = {}
            for ev in tev:
                ph = ev.get("ph")
                if ph == "M":
                    continue
                ts = ev.get("ts")
                if not isinstance(ts, int) or ts < 0:
                    problems.append(f"{TRACE_FILE}: bad ts in {ev}")
                    break
                if ts < last_ts:
                    problems.append(
                        f"{TRACE_FILE}: ts not monotonic at {ev}")
                    break
                last_ts = ts
                key = (ev.get("pid", 0), ev.get("tid", 0))
                if ph == "B":
                    depth[key] = depth.get(key, 0) + 1
                elif ph == "E":
                    depth[key] = depth.get(key, 0) - 1
                    if depth[key] < 0:
                        problems.append(
                            f"{TRACE_FILE}: E without B on track {key}")
                        break
            for key, d in depth.items():
                if d != 0:
                    problems.append(
                        f"{TRACE_FILE}: {d} unmatched B event(s) on "
                        f"track {key}")
    try:
        flight_dumps = sorted(
            f for f in os.listdir(run_dir)
            if f.startswith("flight-") and f.endswith(".json"))
    except OSError:
        flight_dumps = []
    for name in flight_dumps:
        problems.extend(check_flight_dump(os.path.join(run_dir, name)))
    if problems:
        for p in problems:
            print(f"self-check FAIL: {p}", file=sys.stderr)
        return 1
    print(f"self-check OK: {run_dir} ({len(events)} events)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="teldiff", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--self-check", metavar="RUN_DIR_OR_DUMP",
                    help="validate a run's artifacts (or a flight-*.json "
                         "dump) and exit")
    sub = ap.add_subparsers(dest="cmd")
    p_sum = sub.add_parser("summarize", help="print one run's summary")
    p_sum.add_argument("run_dir")
    p_diff = sub.add_parser("diff", help="compare two runs")
    p_diff.add_argument("base_dir")
    p_diff.add_argument("cand_dir")
    p_diff.add_argument("--threshold", type=float, default=10.0,
                        help="regression threshold in percent (default 10)")
    args = ap.parse_args(argv)
    if args.self_check:
        return self_check(args.self_check)
    if args.cmd == "summarize":
        return summarize(args.run_dir)
    if args.cmd == "diff":
        return diff(args.base_dir, args.cand_dir, args.threshold)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
